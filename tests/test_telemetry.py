"""Telemetry layer tests: metrics registry (labels/buckets/exposition),
step-trace spans, retrace watchdog, and the publisher integrations
(trainer, kvstore tpu_ici, serve) — ISSUE 2."""
import json
import logging
import re
import threading

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler, telemetry
from mxnet_tpu.gluon import nn


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_counter_labels():
    reg = telemetry.MetricsRegistry()
    c = reg.counter("req_total", "requests", ("endpoint", "event"))
    c.labels(endpoint="a", event="ok").inc()
    c.labels(endpoint="a", event="ok").inc(2)
    c.labels("a", "err").inc()
    assert c.labels(endpoint="a", event="ok").value == 3
    assert reg.get_sample_value(
        "req_total", {"endpoint": "a", "event": "err"}) == 1
    # unknown combination reads as absent
    assert reg.get_sample_value(
        "req_total", {"endpoint": "b", "event": "ok"}) is None
    with pytest.raises(ValueError):
        c.inc()          # labeled family needs .labels()
    with pytest.raises(ValueError):
        c.labels(endpoint="a").inc()   # missing label
    with pytest.raises(ValueError):
        c.labels(endpoint="a", event="ok").inc(-1)  # counters go up


def test_registry_gauge_and_reregistration():
    reg = telemetry.MetricsRegistry()
    g = reg.gauge("depth", "queue depth")
    g.set(5)
    g.dec(2)
    assert g.value == 3
    # get-or-create returns the same family; kind mismatch raises
    assert reg.gauge("depth") is g
    with pytest.raises(ValueError):
        reg.counter("depth")


def test_registry_histogram_buckets():
    reg = telemetry.MetricsRegistry()
    h = reg.histogram("lat_seconds", "latency", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    # cumulative bucket semantics: le is inclusive
    assert reg.get_sample_value("lat_seconds_bucket", {"le": "0.01"}) == 1
    assert reg.get_sample_value("lat_seconds_bucket", {"le": "0.1"}) == 2
    assert reg.get_sample_value("lat_seconds_bucket", {"le": "1"}) == 3
    assert reg.get_sample_value("lat_seconds_bucket", {"le": "+Inf"}) == 4
    assert reg.get_sample_value("lat_seconds_count", {}) == 4
    assert reg.get_sample_value("lat_seconds_sum", {}) == \
        pytest.approx(5.555)
    # an observation exactly on a bound lands in that bucket
    h.observe(0.1)
    assert reg.get_sample_value("lat_seconds_bucket", {"le": "0.1"}) == 3


_PROM_LINE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s([-+0-9.eE]+|[+-]Inf)$')


def _parse_prometheus(text):
    """{(sample_name, frozenset(label items)): value}"""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        name, labels, value = m.groups()
        items = frozenset(
            tuple(kv.split("=", 1)) for kv in labels.split(",")) \
            if labels else frozenset()
        items = frozenset((k, v.strip('"')) for k, v in items)
        out[(name, items)] = float(value)
    return out


def test_exposition_roundtrip():
    """Prometheus text and JSON exposition carry the same samples."""
    reg = telemetry.MetricsRegistry()
    reg.counter("a_total", 'with "quotes" and \\slash', ("k",)) \
        .labels(k='va"l').inc(7)
    reg.gauge("b").set(-2.5)
    h = reg.histogram("c_seconds", "h", ("p",), buckets=(0.5,))
    h.labels(p="x").observe(0.25)
    h.labels(p="x").observe(2.0)

    prom = _parse_prometheus(reg.export_prometheus())
    doc = json.loads(reg.export_json())
    json_samples = {}
    for fam in doc["metrics"]:
        for s in fam["samples"]:
            key = (s["name"], frozenset(
                (k, str(v)) for k, v in s["labels"].items()))
            json_samples[key] = float(s["value"])
    # every prom sample appears in json with the same value (label
    # escaping differs textually, so compare the unescaped json side by
    # count + spot values)
    assert len(prom) == len(json_samples)
    assert json_samples[("b", frozenset())] == -2.5
    assert json_samples[("c_seconds_bucket",
                         frozenset({("p", "x"), ("le", "0.5")}))] == 1
    assert json_samples[("c_seconds_count", frozenset({("p", "x")}))] == 2
    assert prom[("b", frozenset())] == -2.5


def _unescape_label_value(v):
    """Invert text-format 0.0.4 label-value escaping (\\\\, \\", \\n)."""
    out, i = [], 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            n = v[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(n, c + n))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def test_exposition_hostile_label_values_roundtrip():
    """Backslashes, double quotes, and newlines in label VALUES must be
    escaped per the Prometheus text format and parse back to the exact
    original strings — no label may break the line-oriented exposition
    (ISSUE 17 satellite)."""
    hostile = [
        "back\\slash", 'quo"te', "new\nline",
        'all\\three" \n mixed', "\\n literal backslash-n",
        "trailing backslash\\", '"', "\n", "\\",
        'fake closer"} 9',
    ]
    reg = telemetry.MetricsRegistry()
    c = reg.counter("hostile_total", "hostile labels", ("v",))
    for i, val in enumerate(hostile):
        c.labels(v=val).inc(i + 1)
    text = reg.export_prometheus()
    # line-oriented: raw newlines inside values never split a sample
    sample_lines = [ln for ln in text.splitlines()
                    if ln.startswith("hostile_total{")]
    assert len(sample_lines) == len(hostile)
    got = {}
    prefix = 'hostile_total{v="'
    for line in sample_lines:
        assert line.startswith(prefix), line
        escaped, value = line[len(prefix):].rsplit('"} ', 1)
        assert "\n" not in escaped
        got[_unescape_label_value(escaped)] = float(value)
    assert got == {val: float(i + 1) for i, val in enumerate(hostile)}
    # and the registry reads every hostile combination back untouched
    for i, val in enumerate(hostile):
        assert reg.get_sample_value("hostile_total", {"v": val}) == i + 1


def test_registry_thread_safety():
    reg = telemetry.MetricsRegistry()
    c = reg.counter("n_total")
    h = reg.histogram("n_seconds", buckets=(0.5,))

    def work():
        for _ in range(20000):
            c.inc()
            h.observe(0.1)

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 80000
    assert reg.get_sample_value("n_seconds_count", {}) == 80000


# ---------------------------------------------------------------------------
# retrace / compile watchdog
# ---------------------------------------------------------------------------

def test_watchdog_flags_forced_rejit(caplog):
    import jax
    import jax.numpy as jnp

    reg = telemetry.MetricsRegistry()
    wd = telemetry.RetraceWatchdog(steady_after=1, registry=reg)
    f = wd.watch(jax.jit(lambda x: x * 2), name="double")
    f(jnp.ones((3,)))          # first compile: expected, not a retrace
    f(jnp.ones((3,)))          # cached
    assert wd.retrace_count("double") == 0
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu.telemetry"):
        f(jnp.ones((4,)))      # shape drift past steady state -> re-jit
    assert wd.retrace_count("double") == 1
    assert reg.get_sample_value(
        "mxtpu_jit_retrace_total", {"fn": "double"}) == 1
    warnings = [r for r in caplog.records if "double" in r.getMessage()]
    assert warnings and "recompile" in warnings[0].getMessage()


def test_watchdog_quiet_before_steady_state(caplog):
    import jax
    import jax.numpy as jnp

    reg = telemetry.MetricsRegistry()
    wd = telemetry.RetraceWatchdog(steady_after=5, registry=reg)
    f = wd.watch(jax.jit(lambda x: x + 1), name="warming")
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu.telemetry"):
        for n in (2, 3, 4):    # warmup sweep: counted, never warned
            f(jnp.ones((n,)))
    assert wd.retrace_count("warming") == 2
    assert not [r for r in caplog.records if "warming" in r.getMessage()]


def test_compile_listener_counts_xla_compiles():
    import jax
    import jax.numpy as jnp

    reg = telemetry.default_registry()

    def count():
        return reg.get_sample_value(
            "mxtpu_xla_compile_total", {"stage": "compile"}) or 0

    before = count()
    jax.jit(lambda x: x * 3.5 + 1)(jnp.ones((5,)))   # fresh fn: must compile
    assert count() >= before + 1
    assert (reg.get_sample_value(
        "mxtpu_xla_compile_seconds_count", {"stage": "compile"}) or 0) > 0


def test_hybrid_block_observed_by_default_watchdog():
    net = nn.Dense(3)
    net.initialize()
    net.hybridize()
    name = "Dense.hybrid_forward"
    before = telemetry.default_registry().get_sample_value(
        "mxtpu_jit_retrace_total", {"fn": name}) or 0
    net(mx.np.ones((2, 4)))
    net(mx.np.ones((2, 4)))     # steady
    net(mx.np.ones((6, 4)))     # batch-shape drift forces a re-trace
    after = telemetry.default_registry().get_sample_value(
        "mxtpu_jit_retrace_total", {"fn": name}) or 0
    assert after >= before + 1


# ---------------------------------------------------------------------------
# step-trace spans + trainer phases
# ---------------------------------------------------------------------------

def _train_3_steps(hybridize=True):
    net = nn.Dense(4)
    net.initialize()
    if hybridize:
        net.hybridize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1})
    x = mx.np.array(onp.random.randn(2, 3).astype(onp.float32))
    for _ in range(3):
        with mx.autograd.record():
            loss = (net(x) ** 2).mean()
        loss.backward()
        trainer.step(2)
    return net, x


def test_trainer_step_phases_in_trace():
    profiler.dumps(reset=True)
    profiler.set_state("run")
    _train_3_steps(hybridize=True)
    profiler.set_state("stop")
    events = json.loads(profiler.dumps(format="json", reset=True))[
        "traceEvents"]
    phases = {e["name"] for e in events if e.get("cat") == "step_phase"}
    assert {"step/fwd", "step/bwd", "step/allreduce",
            "step/optimizer"} <= phases
    # op events share the same timeline (the hybrid forward dispatch)
    assert any(e.get("cat") == "operator" for e in events)
    # 3 steps -> at least 3 spans per phase
    fwd = [e for e in events if e.get("name") == "step/fwd"]
    assert len(fwd) >= 3 and all(e.get("dur", 0) >= 0 for e in fwd)
    # while profiling, op dispatches also publish into the registry
    assert "mxtpu_ops_dispatched_total{" in telemetry.export_prometheus()


def test_step_phase_histogram_published():
    before = telemetry.default_registry().get_sample_value(
        "mxtpu_trainer_step_phase_seconds_count", {"phase": "optimizer"}) or 0
    _train_3_steps(hybridize=False)
    after = telemetry.default_registry().get_sample_value(
        "mxtpu_trainer_step_phase_seconds_count", {"phase": "optimizer"})
    assert after == before + 3
    text = telemetry.export_prometheus()
    assert 'mxtpu_trainer_step_phase_seconds_bucket{phase="optimizer"' \
        in text


def test_dataloader_data_wait_phase():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    ds = ArrayDataset(onp.arange(32, dtype=onp.float32).reshape(8, 4))
    loader = DataLoader(ds, batch_size=4)
    before = telemetry.default_registry().get_sample_value(
        "mxtpu_trainer_step_phase_seconds_count", {"phase": "data-wait"}) or 0
    assert len(list(loader)) == 2
    after = telemetry.default_registry().get_sample_value(
        "mxtpu_trainer_step_phase_seconds_count", {"phase": "data-wait"})
    assert after == before + 2


# ---------------------------------------------------------------------------
# kvstore collectives
# ---------------------------------------------------------------------------

def test_tpu_ici_collective_counters():
    kv = mx.kv.create("tpu_ici")
    reg = telemetry.default_registry()
    n_before = reg.get_sample_value(
        "mxtpu_kvstore_collective_total", {"op": "allreduce"}) or 0
    b_before = reg.get_sample_value(
        "mxtpu_kvstore_collective_bytes_total", {"op": "allreduce"}) or 0
    vals = [mx.np.ones((4, 4), ctx=mx.cpu(i)) for i in range(4)]
    kv.pushpull(0, vals)
    assert reg.get_sample_value(
        "mxtpu_kvstore_collective_total", {"op": "allreduce"}) == n_before + 1
    # 4 copies x 16 f32 = 256 payload bytes
    assert reg.get_sample_value(
        "mxtpu_kvstore_collective_bytes_total",
        {"op": "allreduce"}) == b_before + 256
    assert (reg.get_sample_value(
        "mxtpu_kvstore_collective_seconds_count", {"op": "allreduce"}) or 0) \
        >= n_before + 1


def test_tpu_ici_collective_span_in_trace():
    kv = mx.kv.create("tpu_ici")
    profiler.dumps(reset=True)
    profiler.set_state("run")
    vals = [mx.np.ones((2, 2), ctx=mx.cpu(i)) for i in range(2)]
    kv.pushpull(1, vals)
    profiler.set_state("stop")
    events = json.loads(profiler.dumps(format="json", reset=True))[
        "traceEvents"]
    spans = [e for e in events if e.get("cat") == "collective"]
    assert spans and spans[0]["name"] == "collective/allreduce"
    assert spans[0]["args"]["bytes"] == 2 * 2 * 2 * 4


# ---------------------------------------------------------------------------
# serve integration
# ---------------------------------------------------------------------------

def test_serve_series_in_registry():
    net = nn.Dense(4)
    net.initialize()
    ep = net.as_endpoint(max_batch_size=4, max_latency_ms=2)
    try:
        out = ep.predict(mx.np.ones((2, 3)))
        assert out.shape == (2, 4)
    finally:
        ep.shutdown(drain=True)
    reg = telemetry.default_registry()
    labels = {"endpoint": ep.name, "event": "completed"}
    assert reg.get_sample_value("mxtpu_serve_requests_total", labels) == 1
    assert reg.get_sample_value(
        "mxtpu_serve_latency_seconds_count", {"endpoint": ep.name}) == 1
    assert reg.get_sample_value(
        "mxtpu_serve_batch_rows_total",
        {"endpoint": ep.name, "kind": "real"}) == 2
    text = telemetry.export_prometheus()
    assert f'mxtpu_serve_batches_total{{endpoint="{ep.name}"}}' in text


# ---------------------------------------------------------------------------
# the acceptance scenario: ONE dump interleaves every source
# ---------------------------------------------------------------------------

def test_unified_trace_one_dump(tmp_path):
    profiler.dumps(reset=True)
    f = str(tmp_path / "unified.json")
    profiler.set_config(filename=f)
    profiler.set_state("run")

    net, x = _train_3_steps(hybridize=True)           # step phases + ops
    kv = mx.kv.create("tpu_ici")
    kv.pushpull(0, [mx.np.ones((4,), ctx=mx.cpu(i)) for i in range(2)])
    ep = net.as_endpoint(max_batch_size=4, max_latency_ms=2)
    try:
        ep.predict(x)                                  # serve dispatch
    finally:
        ep.shutdown(drain=True)

    profiler.dump()            # finished=True: stops + writes + resets
    assert profiler.state() == "stop"
    events = json.load(open(f))["traceEvents"]
    cats = {e.get("cat") for e in events}
    assert {"step_phase", "operator", "collective", "serve"} <= cats
    serve_spans = [e for e in events if e.get("cat") == "serve"]
    assert serve_spans[0]["args"]["rows"] == 2
    # the dump reset the shared buffer: a fresh dumps() is empty
    assert json.loads(profiler.dumps(format="json"))["traceEvents"] == []
    # registry covers trainer + kvstore + serve series in one scrape
    text = telemetry.export_prometheus()
    for series in ("mxtpu_trainer_step_phase_seconds",
                   "mxtpu_kvstore_collective_total",
                   "mxtpu_serve_requests_total",
                   "mxtpu_xla_compile_total"):
        assert series in text, series


# ---------------------------------------------------------------------------
# profiler satellites
# ---------------------------------------------------------------------------

def test_profiler_counter_concurrent_increments():
    """increment/decrement are read-modify-write: without the lock,
    concurrent serve threads lose updates."""
    c = profiler.Domain("unit").new_counter("hits", 0)

    def work():
        for _ in range(30000):
            c.increment()

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 120000
    c.decrement(120000)
    assert c.value == 0


def test_profiler_scope_enter_failure_leaves_no_dangling_span(monkeypatch):
    class Boom:
        def __init__(self, name):
            pass

        def __enter__(self):
            raise RuntimeError("annotation unavailable")

        def __exit__(self, *exc):
            return False

    import jax
    monkeypatch.setattr(jax.profiler, "TraceAnnotation", Boom)
    profiler.dumps(reset=True)
    profiler.set_state("run")
    sc = profiler.scope("doomed")
    with pytest.raises(RuntimeError):
        sc.__enter__()
    sc.__exit__(None, None, None)     # must not raise nor emit
    profiler.set_state("stop")
    events = json.loads(profiler.dumps(format="json", reset=True))[
        "traceEvents"]
    assert not any(e.get("name") == "doomed" for e in events)


def test_profiler_dump_not_finished_keeps_state(tmp_path):
    profiler.dumps(reset=True)
    profiler.set_config(filename=str(tmp_path / "flush.json"))
    profiler.set_state("run")
    with profiler.scope("keep-me"):
        pass
    profiler.dump(finished=False)     # periodic flush: stays running
    assert profiler.state() == "run"
    with profiler.scope("second"):
        pass
    profiler.set_state("stop")
    events = json.loads(profiler.dumps(format="json", reset=True))[
        "traceEvents"]
    names = {e["name"] for e in events}
    assert {"keep-me", "second"} <= names   # buffer was not reset


# ---------------------------------------------------------------------------
# monitor satellites
# ---------------------------------------------------------------------------

def test_monitor_toc_print_fixed_precision(caplog):
    from mxnet_tpu.monitor import Monitor

    net = nn.Dense(2)
    net.initialize()
    mon = Monitor(interval=1).install(net)
    mon.tic()
    net(mx.np.ones((1, 3)))
    with caplog.at_level(logging.INFO):
        mon.toc_print()
    stats = [r.getMessage() for r in caplog.records
             if r.getMessage().startswith("Batch:")]
    assert stats
    for line in stats:
        assert re.search(r"\d+\.\d{6}$", line), line
    mon.uninstall()


def test_block_children_public_iteration():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    kids = net.children
    assert isinstance(kids, dict) and len(kids) == 2
    assert all(isinstance(c, mx.gluon.Block) for c in kids.values())
    # Monitor.install walks through the public surface
    from mxnet_tpu.monitor import Monitor
    net.initialize()
    mon = Monitor(interval=1).install(net)
    mon.tic()
    net(mx.np.ones((1, 3)))
    names = {n for _s, n, _v in mon.toc()}
    assert any(".0_output" in n for n in names)
    mon.uninstall()
