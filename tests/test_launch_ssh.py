"""ssh launcher transport test (reference `tools/launch.py:72-74`).

No sshd exists in CI, so the transport is exercised through a fake `ssh`
binary that strips the options/hostname and runs the remote command in a
local shell — validating exactly what the launcher is responsible for:
rank/coordinator env wiring inlined into the ssh command line, round-robin
host assignment, and exit-code aggregation.
"""
import json
import os
import stat
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import launch  # noqa: E402


FAKE_SSH = """#!/usr/bin/env python3
import subprocess, sys
# drop ssh options ("-o value" pairs), then the hostname; run the rest
args = sys.argv[1:]
while args and args[0] == "-o":
    args = args[2:]
host, remote = args[0], " ".join(args[1:])
with open(__OUT__ + "/hosts.log", "a") as f:
    f.write(host + "\\n")
sys.exit(subprocess.call(["/bin/sh", "-c", remote]))
"""


@pytest.fixture
def fake_ssh(tmp_path):
    path = tmp_path / "fake-ssh"
    path.write_text(FAKE_SSH.replace("__OUT__", repr(str(tmp_path))))
    path.chmod(path.stat().st_mode | stat.S_IEXEC)
    return path


def test_parse_hostfile(tmp_path):
    hf = tmp_path / "hosts"
    hf.write_text("# pod hosts\nhost-a slots=4\nhost-b\n\nhost-c  # tail\n")
    assert launch.parse_hostfile(str(hf)) == ["host-a", "host-b", "host-c"]
    empty = tmp_path / "empty"
    empty.write_text("# nothing\n")
    with pytest.raises(ValueError):
        launch.parse_hostfile(str(empty))


def test_ssh_launch_env_wiring(tmp_path, fake_ssh):
    probe = tmp_path / "probe.py"
    probe.write_text(
        "import json, os\n"
        "rec = {k: os.environ.get(k) for k in ('JAX_COORDINATOR_ADDRESS',"
        " 'JAX_NUM_PROCESSES', 'JAX_PROCESS_ID', 'DMLC_WORKER_ID',"
        " 'EXTRA_FLAG')}\n"
        "path = os.path.join(%r, 'rank%%s.json' %% rec['JAX_PROCESS_ID'])\n"
        "json.dump(rec, open(path, 'w'))\n" % str(tmp_path))
    codes = launch.launch_ssh(
        4, [sys.executable, str(probe)], ["node0", "node1"],
        coordinator_port=5123, env_extra={"EXTRA_FLAG": "on"},
        ssh_binary=str(fake_ssh))
    assert codes == [0, 0, 0, 0]
    hosts = (tmp_path / "hosts.log").read_text().split()
    assert sorted(hosts) == ["node0", "node0", "node1", "node1"]
    for rank in range(4):
        rec = json.load(open(tmp_path / f"rank{rank}.json"))
        assert rec["JAX_COORDINATOR_ADDRESS"] == "node0:5123"
        assert rec["JAX_NUM_PROCESSES"] == "4"
        assert rec["JAX_PROCESS_ID"] == str(rank)
        assert rec["DMLC_WORKER_ID"] == str(rank)
        assert rec["EXTRA_FLAG"] == "on"


def test_ssh_launch_remote_cwd_keeps_env(tmp_path, fake_ssh):
    """`cd DIR && env VARS cmd` — the env must bind to the command, not
    to `cd` (r4 review finding)."""
    workdir = tmp_path / "wd"
    workdir.mkdir()
    probe = tmp_path / "probe.py"
    probe.write_text(
        "import os\n"
        "open(os.path.join(%r, 'cwd_env.txt'), 'w').write(\n"
        "    os.getcwd() + '|' + os.environ['JAX_PROCESS_ID'])\n"
        % str(tmp_path))
    codes = launch.launch_ssh(
        1, [sys.executable, str(probe)], ["h0"],
        ssh_binary=str(fake_ssh), remote_cwd=str(workdir))
    assert codes == [0]
    cwd, rank = (tmp_path / "cwd_env.txt").read_text().split("|")
    assert os.path.realpath(cwd) == os.path.realpath(str(workdir))
    assert rank == "0"


def test_ssh_launch_propagates_failure(tmp_path, fake_ssh):
    codes = launch.launch_ssh(
        2, [sys.executable, "-c",
            "import os,sys; sys.exit(int(os.environ['JAX_PROCESS_ID']))"],
        ["h0"], ssh_binary=str(fake_ssh))
    assert codes == [0, 1]


def test_cli_ssh_mode(tmp_path, fake_ssh):
    hf = tmp_path / "hosts"
    hf.write_text("localhost\n")
    marker = tmp_path / "ran.txt"
    rc = subprocess.call(
        [sys.executable, launch.__file__, "-n", "1", "--launcher", "ssh",
         "-H", str(hf), "--ssh-binary", str(fake_ssh),
         "--env", "M=1", "--",
         sys.executable, "-c",
         f"import os; open({str(marker)!r}, 'w').write(os.environ['M'])"])
    assert rc == 0
    assert marker.read_text() == "1"
