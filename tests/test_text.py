"""contrib.text tests (reference `tests/python/unittest/test_contrib_text.py`)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import text


def test_count_tokens():
    c = text.utils.count_tokens_from_str("a b  b\nc a a", to_lower=False)
    assert c["a"] == 3 and c["b"] == 2 and c["c"] == 1


def test_vocabulary_ordering_and_limits():
    c = text.utils.count_tokens_from_str("d d d b b c c a")
    v = text.Vocabulary(c, most_freq_count=2, min_freq=2,
                        reserved_tokens=["<pad>"])
    # 0=<unk>, 1=<pad>, then top-2 by freq (ties alphabetical)
    assert v.idx_to_token == ["<unk>", "<pad>", "d", "b"]
    assert v.to_indices("d") == 2
    assert v.to_indices(["a", "d"]) == [0, 2]  # 'a' unknown
    assert v.to_tokens([0, 3]) == ["<unk>", "b"]
    with pytest.raises(ValueError):
        v.to_tokens(99)


def _write_vec(tmp_path, header=False):
    p = tmp_path / "emb.txt"
    lines = []
    if header:
        lines.append("3 4")
    lines += ["hello 1 2 3 4", "world 0.5 0.5 0.5 0.5", "foo -1 0 1 0"]
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_custom_embedding(tmp_path):
    emb = text.embedding.CustomEmbedding(_write_vec(tmp_path))
    assert emb.vec_len == 4
    assert onp.allclose(emb.get_vecs_by_tokens("hello").asnumpy(),
                        [1, 2, 3, 4])
    # unknown -> zeros (init_unknown_vec default)
    assert onp.allclose(emb.get_vecs_by_tokens("nope").asnumpy(), 0)
    vecs = emb.get_vecs_by_tokens(["world", "foo"]).asnumpy()
    assert vecs.shape == (2, 4)
    emb.update_token_vectors("world", mx.np.ones(4))
    assert onp.allclose(emb.get_vecs_by_tokens("world").asnumpy(), 1)


def test_custom_embedding_fasttext_header(tmp_path):
    emb = text.embedding.CustomEmbedding(_write_vec(tmp_path, header=True))
    assert emb.vec_len == 4
    assert len(emb) == 4  # <unk> + 3 tokens


def test_custom_embedding_with_vocabulary(tmp_path):
    c = text.utils.count_tokens_from_str("hello hello unknownword")
    vocab = text.Vocabulary(c)
    emb = text.embedding.CustomEmbedding(_write_vec(tmp_path),
                                         vocabulary=vocab)
    # vocabulary tokens without file vectors stay at zeros
    assert onp.allclose(
        emb.get_vecs_by_tokens("unknownword").asnumpy()[:4], 0)
    assert onp.allclose(emb.get_vecs_by_tokens("hello").asnumpy(),
                        [1, 2, 3, 4])


def test_composite_embedding(tmp_path):
    emb = text.embedding.CustomEmbedding(_write_vec(tmp_path))
    vocab = text.Vocabulary(
        text.utils.count_tokens_from_str("hello world"))
    comp = text.embedding.CompositeEmbedding(vocab, [emb, emb])
    assert comp.vec_len == 8
    v = comp.get_vecs_by_tokens("hello").asnumpy()
    assert onp.allclose(v, [1, 2, 3, 4, 1, 2, 3, 4])


def test_pretrained_names_and_create_gate():
    names = text.embedding.get_pretrained_file_names()
    assert "glove" in names and "fasttext" in names
    with pytest.raises(RuntimeError, match="download"):
        text.embedding.create("glove")
    with pytest.raises(KeyError):
        text.embedding.create("nonsense")
