"""Clean: the daemon thread is retained and joined on close."""
import threading


class Poller:
    def __init__(self):
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._poll, daemon=True)
        self._thread.start()

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _poll(self):
        while not self._stop.wait(1):
            pass
