"""Clean: MXNET_SEED is in env.describe()'s documented table."""
import os

SEED = os.environ.get("MXNET_SEED")
