"""True positive: daemon thread with no join path in the owning class."""
import threading


class Poller:
    def __init__(self):
        self._stop = threading.Event()

    def start(self):
        t = threading.Thread(target=self._poll, daemon=True)
        t.start()                    # never retained, never joined

    def close(self):
        self._stop.set()             # stop event alone does not reap

    def _poll(self):
        while not self._stop.wait(1):
            pass
