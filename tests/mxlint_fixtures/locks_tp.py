"""True positive: lock-owning class mutating shared state unlocked."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0
        self._events = []
        self._by_key = {}

    def bump(self, delta=1):
        self._value += delta          # unlocked read-modify-write

    def record(self, ev):
        self._events.append(ev)       # unlocked container mutation

    def index(self, k, v):
        self._by_key[k] = v           # unlocked subscript store
