"""True positive: os.environ read inside a function body."""
import os


def knob():
    # read at call time: if the caller is ever traced, this bakes in
    return os.environ.get("SOME_KNOB", "0")


def knob_subscript():
    return os.environ["SOME_KNOB"]


def knob_membership():
    return "SOME_KNOB" in os.environ
