"""Clean: env reads at module scope execute once at import."""
import os

KNOB = os.environ.get("SOME_KNOB", "0")
OTHER = os.environ["PATH"] if "PATH" in os.environ else ""


def uses_baked_value():
    return KNOB
