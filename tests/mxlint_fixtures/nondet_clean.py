"""Clean: randomness via jax keys; host reads outside traced regions."""
import random
import time

import jax

from mxnet_tpu.gluon.block import HybridBlock

_T0 = time.time()                      # module scope: host-side, once


@jax.jit
def good_step(x, key):
    noise = jax.random.normal(key, x.shape)   # functional RNG: per-step
    return x + noise


class Net(HybridBlock):
    def forward(self, x, key):
        return x * jax.random.bernoulli(key, 0.9, x.shape)


def host_sampler():
    return random.random(), time.time()   # NOT traced anywhere: fine
