"""Clean counterparts for swallowed-exception: narrow catches, broad
handlers that re-raise, propagate the object, log, print, or tick
telemetry."""
import logging

log = logging.getLogger(__name__)


def narrow(fn):
    try:
        return fn()
    except (ValueError, KeyError):
        return None


def reraises(fn):
    try:
        return fn()
    except Exception:
        raise


def wraps_and_raises(fn):
    try:
        return fn()
    except Exception as exc:
        raise RuntimeError("probe failed") from exc


def propagates_object(fn, q):
    try:
        return fn()
    except Exception as exc:
        q.put(exc)


def logs(fn):
    try:
        return fn()
    except Exception:
        log.warning("probe failed; using fallback")
        return None


def prints(fn):
    try:
        return fn()
    except Exception:
        print("probe failed")


def ticks_telemetry(fn, counter):
    try:
        return fn()
    except Exception:
        counter.inc()
