"""True positive: an MXNET_* knob that env.describe() does not list."""
import os

FLAG = os.environ.get("MXNET_NOT_IN_THE_TABLE", "0")
