"""Clean: static shape math inside jit is host math on Python ints."""
import jax

from mxnet_tpu.gluon.block import HybridBlock


@jax.jit
def good_step(x):
    n = int(x.shape[0])              # static: allowed
    m = float(len(x.shape))          # static: allowed
    return x * (n + m)


class Net(HybridBlock):
    def forward(self, x):
        return x.reshape(int(x.shape[0]), -1)


def host_helper(x):
    return float(x)                  # NOT traced anywhere: fine
