"""True positive: host syncs inside traced functions (never imported)."""
import jax
import numpy as onp

from mxnet_tpu.gluon.block import HybridBlock


@jax.jit
def bad_step(x):
    s = x.sum()
    return s.item()                  # device->host sync under jit


def also_bad(x):
    return float(x)                  # concretizes a tracer


also_bad_jit = jax.jit(also_bad)     # marks also_bad as traced


class Net(HybridBlock):
    def forward(self, x):
        return onp.asarray(x) * 2    # hybridize() would trace this
