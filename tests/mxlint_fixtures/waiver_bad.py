"""A waiver without a reason is itself a finding and waives nothing."""
import os


def knob():
    # mxlint: disable=env-read-at-trace-time
    return os.environ.get("SOME_KNOB")
