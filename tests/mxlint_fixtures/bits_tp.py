"""True positive: int<->float bit reinterpretation outside a codec."""
import jax.numpy as jnp
from jax import lax


def stash_counter(counter, grads):
    payload = counter.view(jnp.float32)           # int bits in a float
    widened = lax.bitcast_convert_type(grads, jnp.int32)
    return payload, widened
