"""Waivers with reasons suppress findings (same-line and line-above)."""
import os


def knob():
    # mxlint: disable=env-read-at-trace-time -- fixture: host-side by contract
    return os.environ.get("SOME_KNOB")


def other():
    return os.environ.get("OTHER_KNOB")  # mxlint: disable=env-read-at-trace-time -- fixture: trailing-comment form
