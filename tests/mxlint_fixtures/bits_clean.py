"""Clean: astype converts values, not bit patterns."""
import jax.numpy as jnp


def widen(x):
    return x.astype(jnp.float32)


def reshape_not_dtype(x):
    # torch-style shape .view is not a bit reinterpretation
    return x.view(2, 3)
