"""True positives for swallowed-exception: broad handlers where the
fault provably goes nowhere."""


def bare_pass(fn):
    try:
        return fn()
    except:  # noqa: E722
        pass


def broad_return(fn):
    try:
        return fn()
    except Exception:
        return None


def bound_but_unused(fn):
    try:
        return fn()
    except Exception as e:
        return None


def broad_in_tuple(fns):
    out = []
    for f in fns:
        try:
            out.append(f())
        except (ValueError, BaseException):
            continue
    return out
