"""Clean: every shared-state mutation happens under the lock."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0
        self._events = []

    def bump(self, delta=1):
        with self._lock:
            self._value += delta

    def record(self, ev):
        with self._lock:
            self._events.append(ev)

    def snapshot(self):
        # reads are not flagged (GIL-atomic; staleness is the caller's
        # problem, lost updates are not)
        return self._value, list(self._events)
