"""True positive: host nondeterminism read at trace time (never imported)."""
import os
import random
import time

import jax
import numpy as onp

from mxnet_tpu.gluon.block import HybridBlock


@jax.jit
def bad_step(x):
    t0 = time.time()                   # baked at trace: constant timestamp
    return x * t0


def bad_dropout(x):
    keep = random.random()             # stdlib RNG: one sample, forever
    noise = onp.random.randn(4)        # numpy global RNG: same
    return x * keep + noise.sum()


bad_dropout_jit = jax.jit(bad_dropout)  # marks bad_dropout as traced


class Net(HybridBlock):
    def forward(self, x):
        seed = os.urandom(4)           # OS entropy baked into the program
        return x * len(seed)
