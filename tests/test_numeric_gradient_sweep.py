"""Broad backward sweep: numeric-gradient oracle over the op surface
(VERDICT r1 missing #8).

Reference: `python/mxnet/test_utils.py:1043` check_numeric_gradient is
the backbone oracle applied across `tests/python/unittest/test_operator
.py`; this sweep applies the same oracle to the differentiable core of
mx.np / mx.npx / mx.nd.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd
from mxnet_tpu.test_utils import check_numeric_gradient


def _rand(*shape, lo=-1.0, hi=1.0, seed=0):
    rs = onp.random.RandomState(seed + sum(shape))
    return mx.np.array((rs.rand(*shape) * (hi - lo) + lo).astype("f"))


# (name, fn, input builders) — positive-domain ops get lo>0
UNARY_CASES = [
    ("exp", lambda x: mx.np.exp(x), dict()),
    ("log", lambda x: mx.np.log(x), dict(lo=0.2, hi=3.0)),
    ("sqrt", lambda x: mx.np.sqrt(x), dict(lo=0.2, hi=3.0)),
    ("rsqrt", lambda x: nd.rsqrt(x), dict(lo=0.3, hi=3.0)),
    ("square", lambda x: mx.np.square(x), dict()),
    ("tanh", lambda x: mx.np.tanh(x), dict()),
    ("sigmoid", lambda x: mx.npx.sigmoid(x), dict()),
    ("relu", lambda x: mx.npx.relu(x), dict(lo=0.1, hi=2.0)),
    ("softsign", lambda x: nd.softsign(x), dict()),
    ("erf", lambda x: mx.npx.erf(x), dict()),
    ("abs-shifted", lambda x: mx.np.abs(x + 2.0), dict(lo=0.0, hi=1.0)),
    ("sin", lambda x: mx.np.sin(x), dict()),
    ("arctan", lambda x: mx.np.arctan(x), dict()),
    ("cbrt", lambda x: mx.np.cbrt(x), dict(lo=0.3, hi=2.0)),
    ("expm1", lambda x: mx.np.expm1(x), dict()),
    ("log1p", lambda x: mx.np.log1p(x), dict(lo=0.0, hi=2.0)),
    ("reciprocal", lambda x: nd.reciprocal(x), dict(lo=0.5, hi=2.0)),
    ("softmax", lambda x: mx.npx.softmax(x, axis=-1), dict()),
    ("log_softmax", lambda x: mx.npx.log_softmax(x, axis=-1), dict()),
    ("hard_sigmoid", lambda x: nd.hard_sigmoid(x), dict(lo=-1.5, hi=1.5)),
    ("LRN", lambda x: nd.LRN(x.reshape(1, 4, 2, 1), nsize=3), dict()),
    ("l2_normalization",
     lambda x: mx.npx.l2_normalization(x.reshape(2, 4)),
     dict(lo=0.3, hi=2.0)),
    ("smooth_l1", lambda x: mx.npx.smooth_l1(x), dict(lo=0.2, hi=2.0)),
    ("sum-exclude",
     lambda x: nd.sum(x.reshape(2, 2, 2), axis=1, exclude=True), dict()),
    ("mean", lambda x: mx.np.mean(x), dict()),
    ("norm", lambda x: nd.norm(x), dict(lo=0.3, hi=2.0)),
    ("prod", lambda x: mx.np.prod(x), dict(lo=0.5, hi=1.5)),
    ("cumsum", lambda x: mx.np.cumsum(x), dict()),
    ("max-smooth",
     lambda x: (mx.npx.softmax(x * 3) * x).sum(), dict()),
    ("transpose", lambda x: mx.np.transpose(x.reshape(2, 4)), dict()),
    ("Reshape-codes",
     lambda x: nd.Reshape(x.reshape(2, 2, 2), shape=(0, -1)), dict()),
    ("slice",
     lambda x: nd.slice(x.reshape(2, 4), begin=(0, 1), end=(2, 3)), dict()),
    ("tile", lambda x: mx.np.tile(x, 2), dict()),
    ("clip-interior", lambda x: nd.clip(x, -10.0, 10.0), dict()),
    ("pad",
     lambda x: nd.Pad(x.reshape(1, 1, 2, 4), mode="constant",
                      pad_width=(0, 0, 0, 0, 1, 1, 1, 1)), dict()),
    ("depth_to_space",
     lambda x: nd.depth_to_space(x.reshape(1, 4, 1, 2), 2), dict()),
    ("gamma-ln", lambda x: mx.npx.gammaln(x), dict(lo=0.5, hi=3.0)),
]


@pytest.mark.parametrize("name,fn,dom", UNARY_CASES,
                         ids=[c[0] for c in UNARY_CASES])
def test_unary_numeric_gradient(name, fn, dom):
    x = _rand(8, **dom)
    check_numeric_gradient(fn, [x])


BINARY_CASES = [
    ("broadcast_add", lambda a, b: nd.broadcast_add(a, b)),
    ("broadcast_mul", lambda a, b: nd.broadcast_mul(a, b)),
    ("broadcast_div", lambda a, b: nd.broadcast_div(a + 2.5, b + 2.5)),
    ("broadcast_maximum-offset",
     lambda a, b: nd.broadcast_maximum(a + 3.0, b)),
    ("hypot", lambda a, b: nd.broadcast_hypot(a + 2.0, b + 2.0)),
    ("dot", lambda a, b: nd.dot(a.reshape(2, 4), b.reshape(4, 2))),
    ("batch_dot",
     lambda a, b: mx.npx.batch_dot(a.reshape(2, 2, 2), b.reshape(2, 2, 2))),
    ("where-fixed",
     lambda a, b: nd.where(mx.np.array([1.0, 0, 1, 0, 1, 0, 1, 0]), a, b)),
    ("matmul", lambda a, b: mx.np.matmul(a.reshape(2, 4), b.reshape(4, 2))),
    ("power", lambda a, b: nd.broadcast_power(a + 2.0, b + 2.0)),
]


@pytest.mark.parametrize("name,fn", BINARY_CASES,
                         ids=[c[0] for c in BINARY_CASES])
def test_binary_numeric_gradient(name, fn):
    a = _rand(8, seed=1)
    b = _rand(8, seed=2)
    check_numeric_gradient(fn, [a, b])


def test_layer_ops_numeric_gradient():
    rs = onp.random.RandomState(0)
    x = mx.np.array(rs.rand(2, 3, 6, 6).astype("f"))
    w = mx.np.array((rs.rand(4, 3, 3, 3) * 0.5).astype("f"))
    b = mx.np.array(rs.rand(4).astype("f"))
    check_numeric_gradient(
        lambda x, w, b: nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4),
        [x, w, b], rtol=2e-2, atol=2e-3)

    d = mx.np.array(rs.rand(4, 6).astype("f"))
    fw = mx.np.array((rs.rand(3, 6) * 0.5).astype("f"))
    fb = mx.np.array(rs.rand(3).astype("f"))
    check_numeric_gradient(
        lambda d, w, b: nd.FullyConnected(d, w, b, num_hidden=3),
        [d, fw, fb])

    g = mx.np.array(onp.ones(3, "f"))
    beta = mx.np.array(onp.zeros(3, "f"))
    check_numeric_gradient(
        lambda x, g, b: mx.npx.layer_norm(x, g, b, axis=-1),
        [mx.np.array(rs.rand(4, 3).astype("f")), g, beta],
        rtol=2e-2, atol=2e-3)

    # pooling through avg (max is kink-free only off ties)
    check_numeric_gradient(
        lambda x: nd.Pooling(x, kernel=(2, 2), stride=(2, 2),
                             pool_type="avg"),
        [mx.np.array(rs.rand(1, 2, 4, 4).astype("f"))])


def test_embedding_and_take_numeric_gradient():
    rs = onp.random.RandomState(4)
    w = mx.np.array(rs.rand(5, 3).astype("f"))
    idx = mx.np.array(onp.array([0, 2, 4, 2]), dtype="int32")
    check_numeric_gradient(
        lambda w: mx.npx.embedding(idx, w, input_dim=5, output_dim=3), [w])
    check_numeric_gradient(lambda w: nd.take(w, idx, axis=0), [w])


def test_batch_norm_train_numeric_gradient():
    """The hand-written single-pass BN VJP (ops/nn.py _bn_train_core) vs
    finite differences and the naive mean/var formulation."""
    import jax.numpy as jnp

    from mxnet_tpu.ops import nn as _nn
    from mxnet_tpu.ops.invoke import invoke

    rs = onp.random.RandomState(9)
    x = mx.np.array(rs.rand(4, 3, 5, 5).astype("f") * 2 - 1)
    g = mx.np.array((rs.rand(3) + 0.5).astype("f"))
    b = mx.np.array(rs.rand(3).astype("f"))
    mm = onp.zeros(3, "f")
    mv = onp.ones(3, "f")

    def fn(x, g, b):
        out = invoke(_nn.batch_norm_train,
                     (x, g, b, 0.9, 1e-5, 1, mx.np.array(mm),
                      mx.np.array(mv)), name="bn")
        return out[0]

    check_numeric_gradient(fn, [x, g, b], rtol=2e-2, atol=2e-3)

    # forward + moving stats match the naive formulation
    out, nm, nv = _nn.batch_norm_train(
        x._data, g._data, b._data, 0.9, 1e-5, 1,
        jnp.asarray(mm), jnp.asarray(mv))
    xf = onp.asarray(x._data)
    mean = xf.mean(axis=(0, 2, 3))
    var = xf.var(axis=(0, 2, 3))
    ref = (xf - mean.reshape(1, 3, 1, 1)) / onp.sqrt(
        var.reshape(1, 3, 1, 1) + 1e-5) * onp.asarray(g._data).reshape(
        1, 3, 1, 1) + onp.asarray(b._data).reshape(1, 3, 1, 1)
    onp.testing.assert_allclose(onp.asarray(out), ref, rtol=2e-4, atol=2e-5)
    onp.testing.assert_allclose(onp.asarray(nm), 0.1 * mean, rtol=1e-4)
    onp.testing.assert_allclose(onp.asarray(nv), 0.9 + 0.1 * var, rtol=1e-4)
