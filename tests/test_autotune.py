"""Autotune cache, choke point, driver gate, and bit-parity contracts.

The load-bearing promises from docs/AUTOTUNE.md:

* the cache is a committed, diffable JSON artifact with stable keys
  (round-trips byte-identically through save/load);
* a miss — unknown key, missing file, toolchain-fingerprint mismatch —
  falls back to the documented static default with ONE AutotuneMiss
  warning, never a crash and never an in-process sweep;
* the CI gate (tools/autotune) FAILS on stale entries instead of
  silently ignoring them;
* switching a kernel between its default and tuned params never moves
  a bit: the q-block split of flash attention and the (tm, tn) tiling
  of the s2d stem matmul are numerics-free choices, fwd AND bwd.
"""
import json
import warnings

import numpy as onp
import pytest

from mxnet_tpu import tune
from mxnet_tpu.tune.cache import empty_cache

pytestmark = pytest.mark.serial  # shared tune._memo + env vars


@pytest.fixture(autouse=True)
def _fresh_memo(monkeypatch):
    """Every test sees an un-memoized choke point and controls the
    cache path explicitly (never the committed repo cache)."""
    monkeypatch.delenv("MXNET_AUTOTUNE", raising=False)
    tune.invalidate()
    yield
    tune.invalidate()


def _write_cache(path, entries, fingerprint=None):
    doc = empty_cache()
    if fingerprint is not None:
        doc["fingerprint"] = fingerprint
    doc["entries"] = entries
    tune.save_cache(doc, str(path))
    return str(path)


# --------------------------------------------------------------------------
# cache document: schema, keys, round-trip
# --------------------------------------------------------------------------
def test_cache_roundtrip_byte_stable(tmp_path):
    sig = tune.signature("bfloat16", device="tpu-v5e", b=8, h=8, t=4096,
                         d=64)
    key = tune.make_key("flash_attention", sig)
    assert key == "flash_attention|b8.d64.h8.t4096|bf16|tpu-v5e"
    assert tune.split_key(key) == ("flash_attention", "b8.d64.h8.t4096",
                                   "bf16", "tpu-v5e")
    p = tmp_path / "cache.json"
    _write_cache(p, {key: {"params": {"block_q": 512, "block_k": 1024},
                           "mode": "model", "speedup_vs_default": 1.0}})
    doc = tune.load_cache(str(p))
    assert doc["schema"] == tune.SCHEMA
    assert doc["entries"][key]["params"] == {"block_q": 512,
                                             "block_k": 1024}
    # canonical formatting: a save of the loaded doc reproduces the file
    first = p.read_bytes()
    tune.save_cache(doc, str(p))
    assert p.read_bytes() == first


def test_signature_buckets_to_pow2():
    # t=1000 and t=1024 share a bucket (and thus a cache entry)
    a = tune.signature("bfloat16", device="tpu-v5e", b=32, t=1000, h=650)
    b = tune.signature("bfloat16", device="tpu-v5e", b=32, t=1024, h=650)
    assert a == b == "b32.h1024.t1024|bf16|tpu-v5e"


def test_load_rejects_malformed(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"schema": "something-else", "entries": {}}))
    with pytest.raises(ValueError):
        tune.load_cache(str(p))
    p.write_text(json.dumps({"schema": tune.SCHEMA,
                             "fingerprint": tune.fingerprint(),
                             "entries": {"only|three|parts":
                                         {"params": {}}}}))
    with pytest.raises(ValueError):
        tune.load_cache(str(p))


# --------------------------------------------------------------------------
# the choke point: miss policy
# --------------------------------------------------------------------------
def test_miss_unknown_key_warns_once_then_silent(tmp_path, monkeypatch):
    sig = tune.signature("bfloat16", device="tpu-v5e", b=8, t=128)
    path = _write_cache(tmp_path / "c.json", {})
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE", path)
    tune.invalidate()
    with pytest.warns(tune.AutotuneMiss, match="no entry"):
        got = tune.best("flash_attention", sig, {"block_q": 512})
    assert got == {"block_q": 512}
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # second lookup must NOT warn
        assert tune.best("flash_attention", sig,
                         {"block_q": 512}) == {"block_q": 512}


def test_fingerprint_mismatch_is_default_plus_warning(tmp_path,
                                                      monkeypatch):
    sig = tune.signature("bfloat16", device="tpu-v5e", b=8, t=128)
    key = tune.make_key("flash_attention", sig)
    path = _write_cache(
        tmp_path / "c.json",
        {key: {"params": {"block_q": 64}, "mode": "time"}},
        fingerprint={"schema": tune.SCHEMA, "jax": "0.0.stale"})
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE", path)
    tune.invalidate()
    # never a crash, never the stale entry — the default, plus ONE warning
    with pytest.warns(tune.AutotuneMiss, match="fingerprint|toolchain"):
        got = tune.best("flash_attention", sig, {"block_q": 512})
    assert got == {"block_q": 512}
    assert tune.lookup("flash_attention", sig) is None
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        tune.best("flash_attention", sig, {"block_q": 512})


def test_missing_cache_file_warns_and_defaults(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE",
                       str(tmp_path / "nowhere.json"))
    tune.invalidate()
    with pytest.warns(tune.AutotuneMiss, match="not found"):
        got = tune.best("stem_s2d", "b8.c64.h64.w64|bf16|tpu-v5e",
                        {"tm": 512, "tn": 128})
    assert got == {"tm": 512, "tn": 128}


def test_autotune_disabled_is_silent(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_AUTOTUNE", "0")
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE",
                       str(tmp_path / "nowhere.json"))
    tune.invalidate()
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        got = tune.best("stem_s2d", "b8.c64.h64.w64|bf16|tpu-v5e",
                        {"tm": 512, "tn": 128})
    assert got == {"tm": 512, "tn": 128}


def test_hit_returns_committed_params(tmp_path, monkeypatch):
    sig = tune.signature("bfloat16", device="tpu-v5e", b=8, t=128)
    key = tune.make_key("flash_attention", sig)
    path = _write_cache(tmp_path / "c.json",
                        {key: {"params": {"block_q": 64, "block_k": 128},
                               "mode": "time"}})
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE", path)
    tune.invalidate()
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # a hit is silent
        got = tune.best("flash_attention", sig, {"block_q": 512,
                                                 "block_k": 1024})
    assert got == {"block_q": 64, "block_k": 128}
    got["block_q"] = 7  # caller mutation must not poison the memo
    assert tune.best("flash_attention", sig, {})["block_q"] == 64


# --------------------------------------------------------------------------
# the driver gate
# --------------------------------------------------------------------------
def test_verify_stale_entry_fails(tmp_path, monkeypatch):
    from tools.autotune import verify_cache
    path = _write_cache(
        tmp_path / "c.json",
        {"no_such_kernel|b8.t128|bf16|tpu-v5e":
         {"params": {"x": 1}, "mode": "time"}})
    findings, _ = verify_cache(path=path, kernels_filter=["no_such_kernel"])
    assert [f["rule"] for f in findings] == ["stale-entry"]

    from tools.autotune.driver import main
    monkeypatch.setattr("sys.argv", ["autotune"])
    assert main(["--cache", path, "--kernel", "flash_attention"]) == 1


def test_verify_params_not_in_grid_is_stale(tmp_path):
    from tools.autotune import verify_cache
    spec = tune.get("flash_attention")
    sig = spec.signatures()[0]
    key = tune.make_key("flash_attention", sig)
    path = _write_cache(
        tmp_path / "c.json",
        {key: {"params": {"block_q": 96, "block_k": 96}, "mode": "time"}})
    findings, _ = verify_cache(path=path,
                               kernels_filter=["flash_attention"])
    rules = {f["rule"] for f in findings}
    assert "stale-entry" in rules


def test_verify_fingerprint_mismatch_fails(tmp_path):
    from tools.autotune import verify_cache
    path = _write_cache(tmp_path / "c.json", {},
                        fingerprint={"schema": tune.SCHEMA,
                                     "jax": "0.0.stale"})
    findings, _ = verify_cache(path=path, kernels_filter=["stem_s2d"])
    assert "fingerprint" in {f["rule"] for f in findings}


@pytest.mark.slow
def test_committed_cache_verifies_clean():
    """The repo's own tools/autotune_cache.json passes the full gate —
    coverage, no stale entries, model winners re-derived bit-for-bit."""
    from tools.autotune import verify_cache
    findings, info = verify_cache()
    assert findings == [], findings
    assert info["entries"] >= 5


# --------------------------------------------------------------------------
# _pick_block regressions (satellite: the old floor-128 fallback)
# --------------------------------------------------------------------------
def test_pick_block_384():
    from mxnet_tpu.ops.pallas_kernels import _pick_block
    # within budget, whole T is one block; over budget, 384 = 2^7 * 3
    # steps down to its largest pow2 divisor <= want, never up
    assert _pick_block(384, 512) == 384
    assert _pick_block(384, 256) == 128
    assert _pick_block(384, 64) == 64


def test_pick_block_1000_small_divisor_not_whole_t():
    from mxnet_tpu.ops.pallas_kernels import _pick_block
    # 1000 = 8 * 125: no pow2 divisor >= 128 exists.  The old floor-128
    # fallback returned the whole T — a single-block kernel whose (T, T)
    # f32 score tile blows VMEM at large T.  The fix walks down to 8.
    assert _pick_block(1000, 512) == 8
    assert 1000 % _pick_block(1000, 512) == 0
    # odd T genuinely has no pow2 divisor: degenerate single block
    assert _pick_block(999, 512) == 999


# --------------------------------------------------------------------------
# bit-parity: tuned params never move a bit
# --------------------------------------------------------------------------
def _flash_qkv(t=256, b=1, h=2, d=16):
    rng = onp.random.RandomState(3)
    return [rng.randn(b, h, t, d).astype(onp.float32) for _ in range(3)]


def test_flash_tuned_vs_default_bit_parity_fwd_bwd(tmp_path, monkeypatch):
    """A cached block_q winner is bitwise-identical to the static
    default in the forward output and dq (the q split never changes
    their accumulation order; block_k is pinned because the k split
    reassociates the softmax accumulation).  dk/dv DO accumulate
    across q-blocks — there a block_q change reorders the f32 sums,
    so the contract is allclose, not bit equality (the committed flash
    winner equals the default, so shipped dispatch is bit-stable
    everywhere anyway)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.pallas_kernels import flash_attention

    q, k, v = (jnp.asarray(x) for x in _flash_qkv())
    sig = tune.signature(q.dtype, b=1, h=2, t=256, d=16)
    key = tune.make_key("flash_attention", sig)
    path = _write_cache(tmp_path / "c.json",
                        {key: {"params": {"block_q": 64, "block_k": 64},
                               "mode": "time"}})
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE", path)
    tune.invalidate()

    def run(fn):
        out = fn(q, k, v)

        def f(q, k, v):
            return fn(q, k, v).astype(jnp.float32).sum()
        _, grads = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
        return out, grads

    out_t, (gq_t, gk_t, gv_t) = run(
        lambda q, k, v: flash_attention(q, k, v))           # cache: bq=64
    out_d, (gq_d, gk_d, gv_d) = run(
        lambda q, k, v: flash_attention(q, k, v,
                                        block_q=128, block_k=64))
    assert onp.array_equal(onp.asarray(out_t), onp.asarray(out_d))
    assert onp.array_equal(onp.asarray(gq_t), onp.asarray(gq_d))
    for ga, gb in ((gk_t, gk_d), (gv_t, gv_d)):
        onp.testing.assert_allclose(onp.asarray(ga), onp.asarray(gb),
                                    rtol=1e-5, atol=1e-6)


def test_stem_tuned_vs_default_bit_parity_fwd_bwd():
    """Every (tm, tn) stem tile choice is bit-identical fwd and bwd:
    K is never split, and the backward is tile-independent XLA dots."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.stem import (fold_stem_kernel, space_to_depth2,
                                    stem_conv_pallas)

    rng = onp.random.RandomState(5)
    x = jnp.asarray(rng.randn(2, 3, 32, 32).astype(onp.float32))
    w7 = jnp.asarray(rng.randn(16, 3, 7, 7).astype(onp.float32))
    xs = space_to_depth2(x)
    wf = fold_stem_kernel(w7)

    def loss(tm, tn):
        def f(xs, wf):
            return stem_conv_pallas(xs, wf, tm=tm, tn=tn).astype(
                jnp.float32).sum()
        return jax.value_and_grad(f, argnums=(0, 1))

    val_a, grads_a = loss(512, 128)(xs, wf)     # static default
    val_b, grads_b = loss(64, 8)(xs, wf)        # a very different tiling
    assert onp.array_equal(onp.asarray(val_a), onp.asarray(val_b))
    for ga, gb in zip(grads_a, grads_b):
        assert onp.array_equal(onp.asarray(ga), onp.asarray(gb))


def test_lstm_cast_bf16_both_layers_sign_bf16():
    """`_RNNLayer.cast` must retarget self._dtype (reference behavior):
    otherwise begin_state() emits float32 initial states, the scan carry
    promotes every gate op, layer >= 1 of a bf16 model silently computes
    in f32 — and the lstm_cell autotune lookup misses on dtype."""
    import mxnet_tpu as mx
    from mxnet_tpu import tune
    from mxnet_tpu.gluon import rnn

    lstm = rnn.LSTM(64, num_layers=2, layout="TNC", input_size=64)
    lstm.initialize()
    lstm.cast("bfloat16")
    x = mx.np.array(onp.random.RandomState(0).randn(5, 2, 64),
                    dtype="bfloat16")

    seen = []
    orig = tune.best

    def spy(kernel, sig, default):
        seen.append((kernel, sig))
        return orig(kernel, sig, default)

    tune.best = spy
    try:
        out = lstm(x)
    finally:
        tune.best = orig
    assert str(out.dtype) == "bfloat16"
    assert len(seen) == 2                     # one consult per layer
    for kernel, sig in seen:
        assert kernel == "lstm_cell"
        assert "|bf16|" in sig, sig           # layer 1 used to sign f32
