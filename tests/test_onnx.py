"""ONNX export/import round trip (VERDICT r1 missing #7).

Reference: `python/mxnet/contrib/onnx/` mx2onnx/onnx2mx.  With no onnx
package available, correctness is established by (a) round-tripping
through our own encoder/decoder with numerical equality, and (b)
checking the emitted wire bytes field-by-field against the onnx.proto
schema for a known small graph.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import onnx as mxonnx
from mxnet_tpu.contrib.onnx import proto as P


def _np(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else onp.asarray(x)


def test_mlp_round_trip(tmp_path):
    sym = mx.sym
    rs = onp.random.RandomState(0)
    x = sym.var("data")
    h = sym.FullyConnected(data=x, weight=sym.var("w1"), bias=sym.var("b1"),
                           num_hidden=8, flatten=False)
    h = sym.Activation(h, act_type="relu")
    out = sym.FullyConnected(data=h, weight=sym.var("w2"),
                             bias=sym.var("b2"), num_hidden=3,
                             flatten=False)
    out = sym.softmax(out, axis=-1)

    params = {"w1": mx.np.array(rs.rand(8, 5).astype("f")),
              "b1": mx.np.array(rs.rand(8).astype("f")),
              "w2": mx.np.array(rs.rand(3, 8).astype("f")),
              "b2": mx.np.array(rs.rand(3).astype("f"))}
    data = rs.rand(4, 5).astype("f")
    ref = out.eval(data=mx.np.array(data), **params)[0]

    path = str(tmp_path / "mlp.onnx")
    mxonnx.export_model(out, params, input_shapes={"data": (4, 5)},
                        onnx_file_path=path)

    sym2, args, aux = mxonnx.import_model(path)
    assert not aux
    assert sorted(args) == ["b1", "b2", "w1", "w2"]
    got = sym2.eval(data=mx.np.array(data), **args)[0]
    onp.testing.assert_allclose(_np(got), _np(ref), rtol=1e-5)


def test_convnet_round_trip(tmp_path):
    sym = mx.sym
    rs = onp.random.RandomState(1)
    x = sym.var("data")
    c = sym.Convolution(data=x, weight=sym.var("cw"), bias=sym.var("cb"),
                        kernel=(3, 3), num_filter=4, pad=(1, 1))
    c = sym.Activation(c, act_type="relu")
    p = sym.Pooling(c, kernel=(2, 2), stride=(2, 2), pool_type="max")
    f = sym.Flatten(p)
    out = sym.FullyConnected(data=f, weight=sym.var("fw"),
                             bias=sym.var("fb"), num_hidden=2,
                             flatten=False)

    params = {"cw": mx.np.array((rs.rand(4, 3, 3, 3) * 0.2).astype("f")),
              "cb": mx.np.array(rs.rand(4).astype("f")),
              "fw": mx.np.array((rs.rand(2, 4 * 4 * 4) * 0.2).astype("f")),
              "fb": mx.np.array(rs.rand(2).astype("f"))}
    data = rs.rand(2, 3, 8, 8).astype("f")
    ref = out.eval(data=mx.np.array(data), **params)[0]

    path = str(tmp_path / "cnn.onnx")
    mxonnx.export_model(out, params, input_shapes={"data": (2, 3, 8, 8)},
                        onnx_file_path=path)
    sym2, args, _aux = mxonnx.import_model(path)
    got = sym2.eval(data=mx.np.array(data), **args)[0]
    onp.testing.assert_allclose(_np(got), _np(ref), rtol=1e-4, atol=1e-5)


def test_batchnorm_embedding_reshape_round_trip(tmp_path):
    sym = mx.sym
    rs = onp.random.RandomState(2)
    idx = sym.var("idx")
    emb = sym.Embedding(data=idx, weight=sym.var("table"), input_dim=10,
                        output_dim=6)
    r = sym.Reshape(emb, shape=(-1, 6))
    bn = sym.BatchNorm(data=r, gamma=sym.var("g"), beta=sym.var("b"),
                       moving_mean=sym.var("mm"), moving_var=sym.var("mv"),
                       axis=1, use_global_stats=True, fix_gamma=False)
    params = {"table": mx.np.array(rs.rand(10, 6).astype("f")),
              "g": mx.np.array(onp.abs(rs.rand(6)).astype("f")),
              "b": mx.np.array(rs.rand(6).astype("f")),
              "mm": mx.np.array(rs.rand(6).astype("f")),
              "mv": mx.np.array((rs.rand(6) + 0.5).astype("f"))}
    data = onp.array([[1, 2], [3, 4]], onp.int32)
    ref = bn.eval(idx=mx.np.array(data, dtype="int32"), **params)[0]

    path = str(tmp_path / "embn.onnx")
    mxonnx.export_model(bn, params, input_shapes={"idx": (2, 2)},
                        onnx_file_path=path)
    sym2, args, aux = mxonnx.import_model(path)
    assert "mm" in aux and "mv" in aux
    got = sym2.eval(idx=mx.np.array(data, dtype="int32"), **args, **aux)[0]
    onp.testing.assert_allclose(_np(got), _np(ref), rtol=1e-4, atol=1e-5)


def test_wire_bytes_follow_onnx_schema(tmp_path):
    """Field-by-field check of the emitted protobuf against onnx.proto
    numbers: ir_version=1, producer=2, graph=7, opset=8; inside the
    graph: node=1, initializer=5, input=11, output=12."""
    sym = mx.sym
    out = sym.relu(sym.var("x"))
    path = str(tmp_path / "t.onnx")
    mxonnx.export_model(out, {}, input_shapes={"x": (2,)},
                        onnx_file_path=path)
    with open(path, "rb") as f:
        blob = f.read()
    fields = {}
    r = P.Reader(blob)
    while not r.eof():
        f_, _w, v = r.field()
        fields.setdefault(f_, []).append(v)
    assert fields[1] == [8]                      # ir_version
    assert fields[2][0] == b"mxnet_tpu"          # producer_name
    assert 7 in fields and 8 in fields           # graph + opset
    g = {}
    r = P.Reader(fields[7][0])
    while not r.eof():
        f_, _w, v = r.field()
        g.setdefault(f_, []).append(v)
    assert 1 in g        # at least one node
    assert 11 in g       # graph input
    assert 12 in g       # graph output
    node = {}
    r = P.Reader(g[1][0])
    while not r.eof():
        f_, _w, v = r.field()
        node.setdefault(f_, []).append(v)
    assert node[4] == [b"Relu"]                  # op_type field 4


def test_new_op_converters_round_trip(tmp_path):
    """Round-3 breadth (VERDICT r2 #4): Pad/Clip/Slice/TopK/Where/
    expand_dims/broadcast_like/Pow/reductions survive export+import."""
    sym = mx.sym
    rs = onp.random.RandomState(3)
    x = sym.var("data")
    y = sym.Pad(x, mode="constant", pad_width=(0, 0, 1, 2),
                constant_value=0.0)
    y = sym.clip(y, 0.1, 0.9)
    y = sym.slice_axis(y, axis=1, begin=1, end=6)
    y = sym.expand_dims(y, axis=0)
    y = sym.squeeze(y, axis=0)
    y = sym.power(y, sym.var("p"))
    y = sym.where(sym.greater(y, sym.var("t")), y, sym.var("t"))
    out = sym.sum(y, axis=1, keepdims=True)

    params = {"p": mx.np.array(onp.full((1,), 2.0, "f")),
              "t": mx.np.array(onp.full((4, 5), 0.25, "f"))}
    data = rs.rand(4, 5).astype("f")
    ref = out.eval(data=mx.np.array(data), **params)[0]
    path = str(tmp_path / "ops.onnx")
    mxonnx.export_model(out, params, input_shapes={"data": (4, 5)},
                        onnx_file_path=path)
    sym2, args, aux = mxonnx.import_model(path)
    got = sym2.eval(data=mx.np.array(data), **args, **aux)[0]
    onp.testing.assert_allclose(_np(got), _np(ref), rtol=1e-5, atol=1e-6)


def test_topk_round_trip(tmp_path):
    sym = mx.sym
    rs = onp.random.RandomState(4)
    x = sym.var("data")
    out = sym.topk(x, k=3, axis=-1, ret_typ="value")
    data = rs.rand(2, 8).astype("f")
    ref = out.eval(data=mx.np.array(data))[0]
    path = str(tmp_path / "topk.onnx")
    mxonnx.export_model(out, {}, input_shapes={"data": (2, 8)},
                        onnx_file_path=path)
    sym2, args, aux = mxonnx.import_model(path)
    got = sym2.eval(data=mx.np.array(data))[0]
    onp.testing.assert_allclose(_np(got), _np(ref), rtol=1e-6)


def test_resnet50_block_round_trip(tmp_path):
    """Model-zoo resnet50_v1 exports via graph capture and re-imports
    numerically (VERDICT r2 #4 done-criterion)."""
    from mxnet_tpu.gluon.model_zoo import vision

    onp.random.seed(0)
    net = vision.resnet50_v1()
    net.initialize()
    x = mx.np.array(onp.random.rand(1, 3, 64, 64).astype("f"))
    ref = net(x).asnumpy()
    path = str(tmp_path / "resnet50.onnx")
    mxonnx.export_block(net, (x,), path)
    sym2, args, aux = mxonnx.import_model(path)
    got = sym2.eval(data=x, **args, **aux)[0].asnumpy()
    onp.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_word_lm_block_round_trip(tmp_path):
    """The word LM (stacked LSTM) round-trips through ONNX LSTM nodes,
    both directions (VERDICT r2 #4 done-criterion)."""
    from mxnet_tpu.models.rnn_lm import RNNModel

    onp.random.seed(0)
    lm = RNNModel(50, num_embed=16, num_hidden=16, num_layers=2,
                  dropout=0.0)
    lm.initialize()
    tokens = mx.np.array(onp.random.randint(0, 50, (5, 2)), dtype="int32")
    ref = lm(tokens).asnumpy()
    path = str(tmp_path / "wordlm.onnx")
    mxonnx.export_block(lm, (tokens,), path, input_names=["data"])
    sym2, args, aux = mxonnx.import_model(path)
    got = sym2.eval(data=tokens, **args, **aux)[0].asnumpy()
    onp.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_gru_block_round_trip(tmp_path):
    from mxnet_tpu.models.rnn_lm import RNNModel

    onp.random.seed(1)
    lm = RNNModel(30, num_embed=12, num_hidden=12, num_layers=1,
                  mode="gru", dropout=0.0)
    lm.initialize()
    tokens = mx.np.array(onp.random.randint(0, 30, (4, 3)), dtype="int32")
    ref = lm(tokens).asnumpy()
    path = str(tmp_path / "gru.onnx")
    mxonnx.export_block(lm, (tokens,), path, input_names=["data"])
    sym2, args, aux = mxonnx.import_model(path)
    got = sym2.eval(data=tokens, **args, **aux)[0].asnumpy()
    onp.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_rnn_relu_block_round_trip(tmp_path):
    """rnn_relu survives the STRINGS 'activations' attribute round trip
    (code-review finding: field-8 parse was missing)."""
    from mxnet_tpu.models.rnn_lm import RNNModel

    onp.random.seed(2)
    lm = RNNModel(20, num_embed=8, num_hidden=8, num_layers=1,
                  mode="rnn_relu", dropout=0.0)
    lm.initialize()
    tokens = mx.np.array(onp.random.randint(0, 20, (4, 2)), dtype="int32")
    ref = lm(tokens).asnumpy()
    path = str(tmp_path / "rnnrelu.onnx")
    mxonnx.export_block(lm, (tokens,), path, input_names=["data"])
    sym2, args, aux = mxonnx.import_model(path)
    got = sym2.eval(data=tokens, **args, **aux)[0].asnumpy()
    onp.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_export_block_positional_scalar_attrs(tmp_path):
    """np.clip(x, 0, 6)-style positional scalars survive capture export
    (code-review finding: they used to collapse to clip(0, 0))."""
    from mxnet_tpu.gluon.block import HybridBlock

    class Relu6(HybridBlock):
        def forward(self, x):
            return mx.np.clip(x * 3.0, 0.0, 2.0)

    net = Relu6()
    net.initialize()
    x = mx.np.array(onp.linspace(-1, 1, 12).astype("f").reshape(3, 4))
    ref = net(x).asnumpy()
    assert ref.max() == 2.0 and ref.min() == 0.0
    path = str(tmp_path / "relu6.onnx")
    mxonnx.export_block(net, (x,), path)
    sym2, args, aux = mxonnx.import_model(path)
    got = sym2.eval(data=x, **args, **aux)[0].asnumpy()
    onp.testing.assert_allclose(got, ref, rtol=1e-6)


def test_export_block_legacy_concat_and_flatten_concat(tmp_path):
    """Captured legacy Concat (capitalized name, axis in closure) exports
    correctly; rank-collapsing concatenate(axis=None) fails loudly
    (code-review findings)."""
    from mxnet_tpu.gluon.block import HybridBlock

    class Cat(HybridBlock):
        def forward(self, a, b):
            return mx.nd.Concat(a, b, dim=0) + 0.0

    net = Cat()
    net.initialize()
    a = mx.np.array(onp.random.rand(2, 3).astype("f"))
    b = mx.np.array(onp.random.rand(2, 3).astype("f"))
    ref = net(a, b).asnumpy()
    path = str(tmp_path / "cat.onnx")
    mxonnx.export_model  # (namespace sanity)
    from mxnet_tpu.contrib.onnx import export_block
    export_block(net, (a, b), path, input_names=["a", "b"])
    sym2, args, aux = mxonnx.import_model(path)
    got = sym2.eval(a=a, b=b, **args, **aux)[0].asnumpy()
    onp.testing.assert_allclose(got, ref, rtol=1e-6)

    class FlattenCat(HybridBlock):
        def forward(self, a, b):
            return mx.np.concatenate([a, b], axis=None) * 1.0

    net2 = FlattenCat()
    net2.initialize()
    with pytest.raises(NotImplementedError, match="rank-collapsing"):
        export_block(net2, (a, b), str(tmp_path / "bad.onnx"),
                     input_names=["a", "b"])
