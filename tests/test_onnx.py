"""ONNX export/import round trip (VERDICT r1 missing #7).

Reference: `python/mxnet/contrib/onnx/` mx2onnx/onnx2mx.  With no onnx
package available, correctness is established by (a) round-tripping
through our own encoder/decoder with numerical equality, and (b)
checking the emitted wire bytes field-by-field against the onnx.proto
schema for a known small graph.
"""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu.contrib import onnx as mxonnx
from mxnet_tpu.contrib.onnx import proto as P


def _np(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else onp.asarray(x)


def test_mlp_round_trip(tmp_path):
    sym = mx.sym
    rs = onp.random.RandomState(0)
    x = sym.var("data")
    h = sym.FullyConnected(data=x, weight=sym.var("w1"), bias=sym.var("b1"),
                           num_hidden=8, flatten=False)
    h = sym.Activation(h, act_type="relu")
    out = sym.FullyConnected(data=h, weight=sym.var("w2"),
                             bias=sym.var("b2"), num_hidden=3,
                             flatten=False)
    out = sym.softmax(out, axis=-1)

    params = {"w1": mx.np.array(rs.rand(8, 5).astype("f")),
              "b1": mx.np.array(rs.rand(8).astype("f")),
              "w2": mx.np.array(rs.rand(3, 8).astype("f")),
              "b2": mx.np.array(rs.rand(3).astype("f"))}
    data = rs.rand(4, 5).astype("f")
    ref = out.eval(data=mx.np.array(data), **params)[0]

    path = str(tmp_path / "mlp.onnx")
    mxonnx.export_model(out, params, input_shapes={"data": (4, 5)},
                        onnx_file_path=path)

    sym2, args, aux = mxonnx.import_model(path)
    assert not aux
    assert sorted(args) == ["b1", "b2", "w1", "w2"]
    got = sym2.eval(data=mx.np.array(data), **args)[0]
    onp.testing.assert_allclose(_np(got), _np(ref), rtol=1e-5)


def test_convnet_round_trip(tmp_path):
    sym = mx.sym
    rs = onp.random.RandomState(1)
    x = sym.var("data")
    c = sym.Convolution(data=x, weight=sym.var("cw"), bias=sym.var("cb"),
                        kernel=(3, 3), num_filter=4, pad=(1, 1))
    c = sym.Activation(c, act_type="relu")
    p = sym.Pooling(c, kernel=(2, 2), stride=(2, 2), pool_type="max")
    f = sym.Flatten(p)
    out = sym.FullyConnected(data=f, weight=sym.var("fw"),
                             bias=sym.var("fb"), num_hidden=2,
                             flatten=False)

    params = {"cw": mx.np.array((rs.rand(4, 3, 3, 3) * 0.2).astype("f")),
              "cb": mx.np.array(rs.rand(4).astype("f")),
              "fw": mx.np.array((rs.rand(2, 4 * 4 * 4) * 0.2).astype("f")),
              "fb": mx.np.array(rs.rand(2).astype("f"))}
    data = rs.rand(2, 3, 8, 8).astype("f")
    ref = out.eval(data=mx.np.array(data), **params)[0]

    path = str(tmp_path / "cnn.onnx")
    mxonnx.export_model(out, params, input_shapes={"data": (2, 3, 8, 8)},
                        onnx_file_path=path)
    sym2, args, _aux = mxonnx.import_model(path)
    got = sym2.eval(data=mx.np.array(data), **args)[0]
    onp.testing.assert_allclose(_np(got), _np(ref), rtol=1e-4, atol=1e-5)


def test_batchnorm_embedding_reshape_round_trip(tmp_path):
    sym = mx.sym
    rs = onp.random.RandomState(2)
    idx = sym.var("idx")
    emb = sym.Embedding(data=idx, weight=sym.var("table"), input_dim=10,
                        output_dim=6)
    r = sym.Reshape(emb, shape=(-1, 6))
    bn = sym.BatchNorm(data=r, gamma=sym.var("g"), beta=sym.var("b"),
                       moving_mean=sym.var("mm"), moving_var=sym.var("mv"),
                       axis=1, use_global_stats=True, fix_gamma=False)
    params = {"table": mx.np.array(rs.rand(10, 6).astype("f")),
              "g": mx.np.array(onp.abs(rs.rand(6)).astype("f")),
              "b": mx.np.array(rs.rand(6).astype("f")),
              "mm": mx.np.array(rs.rand(6).astype("f")),
              "mv": mx.np.array((rs.rand(6) + 0.5).astype("f"))}
    data = onp.array([[1, 2], [3, 4]], onp.int32)
    ref = bn.eval(idx=mx.np.array(data, dtype="int32"), **params)[0]

    path = str(tmp_path / "embn.onnx")
    mxonnx.export_model(bn, params, input_shapes={"idx": (2, 2)},
                        onnx_file_path=path)
    sym2, args, aux = mxonnx.import_model(path)
    assert "mm" in aux and "mv" in aux
    got = sym2.eval(idx=mx.np.array(data, dtype="int32"), **args, **aux)[0]
    onp.testing.assert_allclose(_np(got), _np(ref), rtol=1e-4, atol=1e-5)


def test_wire_bytes_follow_onnx_schema(tmp_path):
    """Field-by-field check of the emitted protobuf against onnx.proto
    numbers: ir_version=1, producer=2, graph=7, opset=8; inside the
    graph: node=1, initializer=5, input=11, output=12."""
    sym = mx.sym
    out = sym.relu(sym.var("x"))
    path = str(tmp_path / "t.onnx")
    mxonnx.export_model(out, {}, input_shapes={"x": (2,)},
                        onnx_file_path=path)
    with open(path, "rb") as f:
        blob = f.read()
    fields = {}
    r = P.Reader(blob)
    while not r.eof():
        f_, _w, v = r.field()
        fields.setdefault(f_, []).append(v)
    assert fields[1] == [8]                      # ir_version
    assert fields[2][0] == b"mxnet_tpu"          # producer_name
    assert 7 in fields and 8 in fields           # graph + opset
    g = {}
    r = P.Reader(fields[7][0])
    while not r.eof():
        f_, _w, v = r.field()
        g.setdefault(f_, []).append(v)
    assert 1 in g        # at least one node
    assert 11 in g       # graph input
    assert 12 in g       # graph output
    node = {}
    r = P.Reader(g[1][0])
    while not r.eof():
        f_, _w, v = r.field()
        node.setdefault(f_, []).append(v)
    assert node[4] == [b"Relu"]                  # op_type field 4
