"""Test configuration.

Mirrors the reference's conftest strategy (`conftest.py:61-119`): seeded RNG
per test for reproducibility and a drain between modules to localize async
failures.  Tests run on a virtual 8-device CPU mesh so multi-chip sharding
paths execute without TPU hardware (the driver separately dry-runs the
multichip path; see `__graft_entry__.py`).
"""
import os

# must be set before jax import
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as onp  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_rng(request):
    seed = onp.random.randint(0, 2 ** 31)
    module_seed = int(os.environ.get("MXNET_TPU_TEST_SEED", seed))
    onp.random.seed(module_seed)
    import mxnet_tpu as mx
    mx.random.seed(module_seed)
    yield
    # drain async work so failures localize to the test that caused them
    # (reference: conftest.py waitall between modules)


def pytest_configure(config):
    config.addinivalue_line("markers", "seed: fixed-seed test")
    config.addinivalue_line("markers", "serial: serial-only test")
