"""Test configuration.

Mirrors the reference's conftest strategy (`conftest.py:61-119`): seeded RNG
per test with the seed logged for repro, and a drain between tests to
localize async failures.  Tests run on a virtual 8-device CPU mesh so
multi-chip sharding paths execute without TPU hardware (the driver
separately dry-runs the multichip path; see `__graft_entry__.py`).
"""
import os

# The axon site hook pre-imports jax and registers the TPU plugin at
# interpreter startup, so env vars alone are read too late; steer the
# platform through jax.config instead.  XLA_FLAGS must still land before
# the first CPU backend is created (it is: no backend exists yet at
# conftest import time).
os.environ["JAX_PLATFORMS"] = "cpu"          # for subprocesses we spawn
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu" and len(jax.devices()) >= 8, (
    "tests must run on the virtual 8-device CPU mesh, got "
    f"{jax.devices()}")

import numpy as onp  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_rng(request):
    seed = onp.random.randint(0, 2 ** 31)
    module_seed = int(os.environ.get("MXNET_TPU_TEST_SEED", seed))
    # log the seed so a flaky failure is reproducible with
    # MXNET_TPU_TEST_SEED=<seed> (reference conftest.py:75-119 prints seeds)
    print(f"[seed {module_seed}]", end=" ", flush=True)
    onp.random.seed(module_seed)
    import mxnet_tpu as mx
    mx.random.seed(module_seed)
    yield
    # drain async work so failures localize to the test that caused them
    # (reference: conftest.py waitall between modules)
    mx.waitall()


@pytest.fixture
def rng():
    """Per-test numpy Generator seeded by the autouse seed fixture."""
    return onp.random.default_rng(onp.random.randint(0, 2 ** 31))


def pytest_configure(config):
    config.addinivalue_line("markers", "seed: fixed-seed test")
    config.addinivalue_line("markers", "serial: serial-only test")
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 'not slow' run")
