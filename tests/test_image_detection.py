"""Detection input pipeline (reference `python/mxnet/image/detection.py:1`
+ `src/io/iter_image_det_recordio.cc:1`): label-transforming augmenters and
ImageDetIter, with label-integrity checks under augmentation (the pattern
of the reference's `tests/python/unittest/test_image.py` ImageDetIter
coverage)."""
import os
import random as pyrandom

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as mimg
from mxnet_tpu.recordio import IRHeader, MXIndexedRecordIO, pack_img


def _sample_label(objs):
    """Pack [cls, x1, y1, x2, y2] rows into the wire format: header=2
    (header_width, obj_width), obj_width=5."""
    flat = [2.0, 5.0]
    for o in objs:
        flat.extend(o)
    return onp.asarray(flat, onp.float32)


def _draw(img, box, value):
    h, w = img.shape[:2]
    x1, y1, x2, y2 = (int(round(box[0] * w)), int(round(box[1] * h)),
                      int(round(box[2] * w)), int(round(box[3] * h)))
    img[y1:y2, x1:x2] = value
    return img


@pytest.fixture(scope="module")
def det_rec(tmp_path_factory):
    """8-image synthetic detection .rec: gray background, one or two
    bright class-colored rectangles per image, packed det labels."""
    root = tmp_path_factory.mktemp("detrec")
    rec_path = str(root / "synth.rec")
    idx_path = str(root / "synth.idx")
    rec = MXIndexedRecordIO(idx_path, rec_path, "w")
    rng = onp.random.RandomState(0)
    truth = []
    for i in range(8):
        img = onp.full((64, 64, 3), 64, onp.uint8)
        objs = []
        for j in range(1 + i % 2):
            x1, y1 = rng.uniform(0.05, 0.5, 2)
            x2, y2 = x1 + rng.uniform(0.2, 0.4), y1 + rng.uniform(0.2, 0.4)
            x2, y2 = min(x2, 0.95), min(y2, 0.95)
            cls = float(j % 2)
            _draw(img, (x1, y1, x2, y2), 255 if cls == 0 else 200)
            objs.append([cls, x1, y1, x2, y2])
        truth.append(objs)
        header = IRHeader(0, _sample_label(objs), i, 0)
        rec.write_idx(i, pack_img(header, img, quality=98))
    rec.close()
    return rec_path, truth


def test_parse_label_wire_format():
    raw = _sample_label([[0, 0.1, 0.2, 0.5, 0.6], [1, 0.3, 0.3, 0.9, 0.8]])
    parsed = mimg.ImageDetIter._parse_label(raw)
    assert parsed.shape == (2, 5)
    onp.testing.assert_allclose(parsed[0], [0, 0.1, 0.2, 0.5, 0.6],
                                rtol=1e-6)
    # degenerate rows (x2<=x1) are dropped
    raw = _sample_label([[0, 0.1, 0.2, 0.5, 0.6], [1, 0.5, 0.5, 0.4, 0.8]])
    assert mimg.ImageDetIter._parse_label(raw).shape == (1, 5)
    with pytest.raises(RuntimeError):
        mimg.ImageDetIter._parse_label(onp.asarray([2, 5, 1], onp.float32))
    with pytest.raises(RuntimeError):  # inconsistent width
        mimg.ImageDetIter._parse_label(
            onp.asarray([2, 5, 0, .1, .2, .3, .4, 9], onp.float32))


def test_flip_is_an_involution():
    aug = mimg.DetHorizontalFlipAug(p=1.0)
    img = onp.random.randint(0, 255, (32, 48, 3)).astype(onp.uint8)
    label = onp.asarray([[0, 0.1, 0.2, 0.4, 0.7],
                         [1, 0.5, 0.1, 0.9, 0.3]], onp.float32)
    img1, lab1 = aug(img.copy(), label.copy())
    img2, lab2 = aug(onp.asarray(img1).copy(), lab1.copy())
    onp.testing.assert_array_equal(onp.asarray(img2), img)
    onp.testing.assert_allclose(lab2, label, rtol=1e-6)
    # flipped boxes still frame the same pixels
    onp.testing.assert_allclose(lab1[:, 1], 1.0 - label[:, 3], rtol=1e-6)
    onp.testing.assert_allclose(lab1[:, 3], 1.0 - label[:, 1], rtol=1e-6)


def test_flip_boxes_track_pixels():
    img = onp.zeros((40, 80, 3), onp.uint8)
    box = (0.25, 0.25, 0.5, 0.75)
    _draw(img, box, 255)
    label = onp.asarray([[0, *box]], onp.float32)
    out, lab = mimg.DetHorizontalFlipAug(p=1.0)(img, label)
    out = onp.asarray(out)
    ys, xs = onp.where(out[:, :, 0] == 255)
    h, w = out.shape[:2]
    got = (xs.min() / w, ys.min() / h, (xs.max() + 1) / w,
           (ys.max() + 1) / h)
    onp.testing.assert_allclose(lab[0, 1:5], got, atol=0.02)


def test_crop_renormalizes_boxes():
    aug = mimg.DetRandomCropAug(min_object_covered=0.9,
                                area_range=(0.3, 1.0), max_attempts=200)
    label = onp.asarray([[0, 0.4, 0.4, 0.6, 0.6]], onp.float32)
    new = aug._clip_labels(label, 16, 16, 32, 32, 64, 64)
    # crop window = normalized (0.25..0.75)^2; box (0.4..0.6) maps to
    # ((0.4-0.25)/0.5 .. ) = 0.3..0.7
    onp.testing.assert_allclose(new[0, 1:5], [0.3, 0.3, 0.7, 0.7],
                                rtol=1e-6)
    # a box fully outside the window is ejected -> None when none left
    label = onp.asarray([[0, 0.0, 0.0, 0.1, 0.1]], onp.float32)
    assert aug._clip_labels(label, 32, 32, 32, 32, 64, 64) is None


def test_crop_keeps_boxes_in_bounds_and_covered():
    pyrandom.seed(3)
    aug = mimg.DetRandomCropAug(min_object_covered=0.5,
                                area_range=(0.2, 0.9),
                                min_eject_coverage=0.3, max_attempts=100)
    img = onp.zeros((64, 64, 3), onp.uint8)
    box = (0.3, 0.3, 0.7, 0.7)
    _draw(img, box, 255)
    label = onp.asarray([[0, *box]], onp.float32)
    crops = flips = 0
    for _ in range(30):
        out, lab = aug(img.copy(), label.copy())
        out = onp.asarray(out)
        assert lab.shape[1] == 5
        assert (lab[:, 1:5] >= -1e-6).all() and (lab[:, 1:5] <= 1 + 1e-6).all()
        assert (lab[:, 3] > lab[:, 1]).all() and (lab[:, 4] > lab[:, 2]).all()
        if out.shape != img.shape:
            crops += 1
            # the surviving box must still frame the bright pixels
            ys, xs = onp.where(out[:, :, 0] == 255)
            if xs.size:
                h, w = out.shape[:2]
                got = (xs.min() / w, ys.min() / h, (xs.max() + 1) / w,
                       (ys.max() + 1) / h)
                onp.testing.assert_allclose(lab[0, 1:5], got, atol=0.06)
    assert crops > 0, "crop never fired in 30 attempts"


def test_pad_tracks_pixels():
    pyrandom.seed(5)
    aug = mimg.DetRandomPadAug(area_range=(1.5, 3.0), pad_val=(10, 10, 10))
    img = onp.zeros((40, 40, 3), onp.uint8)
    box = (0.25, 0.25, 0.75, 0.75)
    _draw(img, box, 255)
    label = onp.asarray([[0, *box]], onp.float32)
    out, lab = aug(img, label)
    out = onp.asarray(out)
    assert out.shape[0] > 40 and out.shape[1] > 40
    ys, xs = onp.where(out[:, :, 0] == 255)
    h, w = out.shape[:2]
    got = (xs.min() / w, ys.min() / h, (xs.max() + 1) / w, (ys.max() + 1) / h)
    onp.testing.assert_allclose(lab[0, 1:5], got, atol=0.03)


def test_borrow_and_select():
    cast = mimg.DetBorrowAug(mimg.CastAug())
    img = onp.random.randint(0, 255, (8, 8, 3)).astype(onp.uint8)
    label = onp.asarray([[0, 0.1, 0.1, 0.9, 0.9]], onp.float32)
    out, lab = cast(img, label)
    assert onp.asarray(out).dtype == onp.float32
    onp.testing.assert_array_equal(lab, label)
    skip = mimg.DetRandomSelectAug([mimg.DetHorizontalFlipAug(1.0)],
                                   skip_prob=1.0)
    out, lab = skip(img, label)
    onp.testing.assert_array_equal(lab, label)


def test_create_det_augmenter_runs_chain():
    pyrandom.seed(11)
    augs = mimg.CreateDetAugmenter((3, 32, 32), rand_crop=0.5, rand_pad=0.5,
                                   rand_mirror=True, mean=True, std=True,
                                   brightness=0.1)
    img = onp.random.randint(0, 255, (50, 70, 3)).astype(onp.uint8)
    label = onp.asarray([[0, 0.2, 0.2, 0.8, 0.8]], onp.float32)
    for _ in range(10):
        out, lab = img, label
        for a in augs:
            out, lab = a(out, lab)
            if lab.shape[0] == 0:
                break
        else:
            out = onp.asarray(out)
            assert out.shape == (32, 32, 3)
            assert out.dtype == onp.float32


def test_image_det_iter_batches(det_rec):
    rec_path, truth = det_rec
    it = mx.image.ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                               path_imgrec=rec_path, aug_list=[
                                   mimg.DetBorrowAug(mimg.ForceResizeAug(
                                       (32, 32)))])
    assert it.provide_label[0].shape == (4, 2, 5)  # max 2 objects, width 5
    batches = list(it)
    assert len(batches) == 2
    b = batches[0]
    assert b.data[0].shape == (4, 3, 32, 32)
    lab = b.label[0].asnumpy()
    assert lab.shape == (4, 2, 5)
    # sample 0 has one object: second row is -1 padding
    assert (lab[0, 1] == -1).all()
    onp.testing.assert_allclose(lab[0, 0], [truth[0][0][i] for i in
                                            range(5)], atol=1e-5)
    # sample 1 has two objects
    assert (lab[1, 1] != -1).any()


def test_image_det_iter_label_integrity_under_flip(det_rec):
    """With deterministic flip augmentation the emitted boxes must frame
    the bright object pixels of the emitted images."""
    rec_path, _ = det_rec
    it = mx.image.ImageDetIter(
        batch_size=8, data_shape=(3, 64, 64), path_imgrec=rec_path,
        aug_list=[mimg.DetHorizontalFlipAug(1.0),
                  mimg.DetBorrowAug(mimg.ForceResizeAug((64, 64)))])
    batch = next(iter(it))
    data = batch.data[0].asnumpy()
    lab = batch.label[0].asnumpy()
    for i in range(8):
        bright = data[i].max(axis=0) > 150
        ys, xs = onp.where(bright)
        x1 = xs.min() / 64
        x2 = (xs.max() + 1) / 64
        rows = lab[i][lab[i, :, 0] >= 0]
        assert rows.shape[0] >= 1
        # leftmost box edge matches leftmost bright pixel (JPEG slack)
        assert abs(rows[:, 1].min() - x1) < 0.08
        assert abs(rows[:, 3].max() - x2) < 0.08


def test_reshape_and_sync_label_shape(det_rec):
    rec_path, _ = det_rec
    a = mx.image.ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                              path_imgrec=rec_path, aug_list=[])
    b = mx.image.ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                              path_imgrec=rec_path, aug_list=[])
    a.reshape(label_shape=(5, 5))
    assert a.provide_label[0].shape == (2, 5, 5)
    with pytest.raises(ValueError):
        a.check_label_shape((1, 5))
    b.sync_label_shape(a)
    assert a.provide_label[0].shape == b.provide_label[0].shape
