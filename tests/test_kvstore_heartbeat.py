"""Heartbeat thread lifecycle (ISSUE 5 satellite).

Before the fix, ``TPUICIStore.close()`` only set the stop event: the
daemon thread object was never retained or joined, so every store
constructed in a test leaked one ``mxtpu-heartbeat`` thread for up to a
full interval (and forever if the event was never set).  mxlint's
``daemon-thread-no-shutdown`` rule now catches the pattern statically;
this is the runtime regression test.
"""
import threading

import pytest

from mxnet_tpu.kvstore.tpu_ici import TPUICIStore


class _FakeKVClient:
    """In-process stand-in for jax.distributed's coordination KV."""

    def __init__(self):
        self.kv = {}

    def key_value_set(self, k, v):
        self.kv[k] = v

    def key_value_delete(self, k):
        self.kv.pop(k, None)

    def key_value_try_get(self, k):
        return self.kv.get(k)


def _hb_threads():
    return [t for t in threading.enumerate()
            if t.name == "mxtpu-heartbeat" and t.is_alive()]


def test_heartbeat_threads_reaped_on_close(monkeypatch):
    """Thread count returns to baseline after close: repeated store
    construction cannot leak one daemon thread per store."""
    client = _FakeKVClient()
    monkeypatch.setenv("MXNET_HEARTBEAT_INTERVAL", "0.05")
    monkeypatch.setattr(TPUICIStore, "_kv_client", lambda self: client)
    baseline = len(_hb_threads())
    stores = []
    for _ in range(5):
        s = TPUICIStore()        # process_count()==1: start explicitly,
        s._start_heartbeat()     # exactly as a size>1 __init__ would
        stores.append(s)
    assert len(_hb_threads()) == baseline + 5
    for s in stores:
        s.close()
    assert len(_hb_threads()) == baseline
    # close is idempotent (reference KVStore contract)
    stores[0].close()
    assert len(_hb_threads()) == baseline


def test_heartbeat_actually_beats_then_stops(monkeypatch):
    client = _FakeKVClient()
    monkeypatch.setenv("MXNET_HEARTBEAT_INTERVAL", "0.01")
    monkeypatch.setattr(TPUICIStore, "_kv_client", lambda self: client)
    s = TPUICIStore()
    s._start_heartbeat()
    assert s._hb_thread is not None and s._hb_thread.is_alive()
    deadline = threading.Event()
    for _ in range(200):
        if any(k.startswith("mxtpu/heartbeat/") for k in client.kv):
            break
        deadline.wait(0.01)
    else:
        pytest.fail("heartbeat never stamped the KV store")
    s.close()
    assert s._hb_thread is None
    assert not _hb_threads()


def test_close_without_heartbeat_is_a_noop():
    s = TPUICIStore()   # single process: no thread started
    assert s._hb_thread is None
    s.close()


def test_liveness_tolerates_clock_skew_under_half_timeout(monkeypatch):
    """Heartbeat stamps carry the SENDER's wall clock, so a peer whose
    clock is off by s makes its beats look s older (or newer).  With a
    beat interval <= timeout/2, any skew under timeout/2 keeps the
    worst-case apparent age (one full interval + skew) below the
    timeout — no rank is ever suspected, let alone declared dead."""
    import time

    client = _FakeKVClient()
    monkeypatch.setattr(TPUICIStore, "_kv_client", lambda self: client)
    s = TPUICIStore()
    monkeypatch.setattr(s, "_size", 2)
    timeout, skew = 10.0, 4.9          # tolerated: skew < timeout/2
    # rank 0's clock runs AHEAD (stamp from the future), rank 1's runs
    # BEHIND and its freshest beat is already a full interval old
    client.kv["mxtpu/heartbeat/0"] = repr(time.time() + skew)
    client.kv["mxtpu/heartbeat/1"] = repr(
        time.time() - (timeout / 2 + skew))
    for _ in range(3):
        assert s.get_dead_nodes(timeout=timeout) == []
    s.close()


def test_liveness_two_observation_rule_absorbs_one_poll_transient(
        monkeypatch):
    import time

    client = _FakeKVClient()
    monkeypatch.setattr(TPUICIStore, "_kv_client", lambda self: client)
    s = TPUICIStore()
    monkeypatch.setattr(s, "_size", 2)
    client.kv["mxtpu/heartbeat/0"] = repr(time.time())
    # one stale poll (beat thread descheduled past the deadline, or
    # skew beyond tolerance for a moment): SUSPECT only
    client.kv["mxtpu/heartbeat/1"] = repr(time.time() - 61)
    assert s.get_dead_nodes(timeout=60) == []
    # the next beat lands: suspicion cleared, no residue
    client.kv["mxtpu/heartbeat/1"] = repr(time.time())
    assert s.get_dead_nodes(timeout=60) == []
    # genuinely dead: stale for two CONSECUTIVE polls — and the earlier
    # transient did not pre-load the counter
    client.kv["mxtpu/heartbeat/1"] = repr(time.time() - 61)
    assert s.get_dead_nodes(timeout=60) == []
    assert s.get_dead_nodes(timeout=60) == [1]
    s.close()
