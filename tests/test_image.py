"""Image augmenter + ImageIter tests (reference test_image.py strategy:
property checks on shapes/ranges rather than pixel-exact values)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as mximg


def _img(h=32, w=48):
    return onp.random.randint(0, 255, (h, w, 3), dtype=onp.uint8)


def test_create_augmenter_pipeline():
    augs = mximg.CreateAugmenter((3, 24, 24), resize=28, rand_crop=True,
                                 rand_mirror=True, brightness=0.2,
                                 contrast=0.2, saturation=0.2, hue=0.1,
                                 pca_noise=0.1, rand_gray=0.2, mean=True,
                                 std=True)
    out = _img()
    for a in augs:
        out = a(out)
    arr = out.asnumpy()
    assert arr.shape == (24, 24, 3)
    assert arr.dtype == onp.float32
    # normalized: roughly centered
    assert abs(arr.mean()) < 3.0


def test_individual_augmenters():
    img = _img(40, 40)
    assert mximg.ResizeAug(20)(img).shape[0] == 20
    assert mximg.ForceResizeAug((10, 16))(img).shape[:2] == (16, 10)
    assert mximg.CenterCropAug((24, 24))(img).shape[:2] == (24, 24)
    assert mximg.RandomCropAug((24, 24))(img).shape[:2] == (24, 24)
    assert mximg.RandomSizedCropAug((24, 24))(img).shape[:2] == (24, 24)
    flipped = mximg.HorizontalFlipAug(1.0)(img).asnumpy()
    assert onp.array_equal(flipped, img[:, ::-1])
    gray = mximg.RandomGrayAug(1.0)(img).asnumpy()
    assert onp.allclose(gray[..., 0], gray[..., 1])
    jit = mximg.ColorJitterAug(0.3, 0.3, 0.3)(img)
    assert jit.shape == img.shape
    hue = mximg.HueJitterAug(0.2)(img)
    assert hue.shape == img.shape
    cast = mximg.CastAug()(img)
    assert cast.dtype == onp.float32


def _make_rec(tmp_path, n=10):
    from mxnet_tpu import recordio
    rec = str(tmp_path / "x.rec")
    idx = str(tmp_path / "x.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), _img()))
    w.close()
    return rec


def test_imageiter_rec(tmp_path):
    rec = _make_rec(tmp_path, 10)
    it = mximg.ImageIter(4, (3, 24, 24), path_imgrec=rec, shuffle=True)
    batches = list(it)
    assert len(batches) == 3  # 10 imgs, pad mode wraps the tail
    assert batches[0].data[0].shape == (4, 3, 24, 24)
    assert batches[0].label[0].shape == (4,)
    assert batches[2].pad == 2
    it.reset()
    assert len(list(it)) == 3


def test_imageiter_imglist(tmp_path):
    from PIL import Image
    root = tmp_path / "imgs"
    root.mkdir()
    lst = tmp_path / "train.lst"
    with open(lst, "w") as f:
        for i in range(6):
            Image.fromarray(_img()).save(root / f"{i}.png")
            f.write(f"{i}\t{float(i % 2)}\t{i}.png\n")
    it = mximg.ImageIter(3, (3, 16, 16), path_imglist=str(lst),
                         path_root=str(root))
    b = next(it)
    assert b.data[0].shape == (3, 3, 16, 16)
    labels = sorted(b.label[0].asnumpy().tolist())
    assert set(labels) <= {0.0, 1.0}


def test_imageiter_sharded_partition_default_seed(tmp_path):
    """REVIEW fix: the default seed=0 is a valid deterministic seed, not
    'no seed' — all parts must draw the SAME global permutation so their
    strided slices form an exact partition."""
    from mxnet_tpu import recordio
    rec = str(tmp_path / "p.rec")
    idx = str(tmp_path / "p.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(9):
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), _img()))
    w.close()

    def labels(part, parts):
        it = mximg.ImageIter(1, (3, 24, 24), path_imgrec=rec, shuffle=True,
                             num_parts=parts, part_index=part,
                             last_batch_handle="discard")
        return [float(b.label[0].asnumpy()[0]) for b in it]

    seen = [labels(p, 3) for p in range(3)]
    assert sorted(sum(seen, [])) == [float(i) for i in range(9)]
    # a fresh construction replays the identical per-part order
    assert labels(1, 3) == seen[1]


def test_imageiter_discard(tmp_path):
    rec = _make_rec(tmp_path, 10)
    it = mximg.ImageIter(4, (3, 24, 24), path_imgrec=rec,
                         last_batch_handle="discard")
    assert len(list(it)) == 2
