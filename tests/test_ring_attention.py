"""Ring attention tests: the sequence-parallel kernel must match dense
attention exactly (it is exact blockwise attention, not an approximation).

Runs on the virtual 8-device CPU mesh from conftest; the sequence axis is
sharded over 'sp' and blocks rotate via ppermute.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel import make_mesh, ring_attention


def _dense_attention(q, k, v, causal=False):
    d = q.shape[-1]
    logits = onp.einsum("bhqd,bhkd->bhqk", q, k) / onp.sqrt(d)
    if causal:
        t_q, t_k = logits.shape[-2:]
        mask = onp.tril(onp.ones((t_q, t_k), bool))
        logits = onp.where(mask, logits, -1e30)
    logits = logits - logits.max(-1, keepdims=True)
    p = onp.exp(logits)
    p = p / p.sum(-1, keepdims=True)
    return onp.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    onp.random.seed(0)
    b, h, t, d = 2, 4, 32, 16  # t sharded 8-way -> 4 per device
    q = onp.random.randn(b, h, t, d).astype(onp.float32)
    k = onp.random.randn(b, h, t, d).astype(onp.float32)
    v = onp.random.randn(b, h, t, d).astype(onp.float32)
    mesh = make_mesh({"sp": 8})
    out = ring_attention(mx.np.array(q), mx.np.array(k), mx.np.array(v),
                         mesh, axis_name="sp", causal=causal)
    expect = _dense_attention(q, k, v, causal=causal)
    assert onp.allclose(out.asnumpy(), expect, atol=2e-4), \
        onp.abs(out.asnumpy() - expect).max()


def test_ring_attention_with_batch_axis():
    """dp x sp mesh: batch sharded over dp, sequence over the sp ring."""
    onp.random.seed(2)
    b, h, t, d = 4, 2, 16, 8
    q = onp.random.randn(b, h, t, d).astype(onp.float32)
    k = onp.random.randn(b, h, t, d).astype(onp.float32)
    v = onp.random.randn(b, h, t, d).astype(onp.float32)
    mesh = make_mesh({"dp": 2, "sp": 4})
    out = ring_attention(mx.np.array(q), mx.np.array(k), mx.np.array(v),
                         mesh, axis_name="sp", batch_axis="dp", causal=True)
    expect = _dense_attention(q, k, v, causal=True)
    assert onp.allclose(out.asnumpy(), expect, atol=2e-4)


def test_ring_attention_long_sequence_scales():
    """Longer-than-memory-per-chip story: T split over the ring; each chip
    only ever holds T/8 of K/V at once."""
    onp.random.seed(1)
    b, h, t, d = 1, 2, 128, 8
    q = onp.random.randn(b, h, t, d).astype(onp.float32)
    k = onp.random.randn(b, h, t, d).astype(onp.float32)
    v = onp.random.randn(b, h, t, d).astype(onp.float32)
    mesh = make_mesh({"sp": 8})
    out = ring_attention(mx.np.array(q), mx.np.array(k), mx.np.array(v),
                         mesh, axis_name="sp")
    expect = _dense_attention(q, k, v)
    assert onp.allclose(out.asnumpy(), expect, atol=2e-4)


def test_ulysses_matches_dense_and_ring():
    """All-to-all sequence parallelism is numerically exact vs dense
    attention and agrees with ring attention on the same shards."""
    import jax
    import numpy as onp

    from mxnet_tpu.parallel import make_mesh, ring_attention, \
        ulysses_attention

    B, H, T, D = 2, 4, 32, 8
    rs = onp.random.RandomState(0)
    q = rs.randn(B, H, T, D).astype("float32")
    k = rs.randn(B, H, T, D).astype("float32")
    v = rs.randn(B, H, T, D).astype("float32")

    mesh = make_mesh({"sp": 4})
    got = onp.asarray(ulysses_attention(q, k, v, mesh, causal=False))

    import jax.numpy as jnp
    s = onp.einsum("bhqd,bhkd->bhqk", q, k) / onp.sqrt(D)
    p = onp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    dense = onp.einsum("bhqk,bhkd->bhqd", p, v)
    onp.testing.assert_allclose(got, dense, rtol=2e-4, atol=2e-5)

    ring = onp.asarray(ring_attention(q, k, v, mesh, causal=False))
    onp.testing.assert_allclose(got, ring, rtol=2e-4, atol=2e-5)

    # causal mode
    got_c = onp.asarray(ulysses_attention(q, k, v, mesh, causal=True))
    ring_c = onp.asarray(ring_attention(q, k, v, mesh, causal=True))
    onp.testing.assert_allclose(got_c, ring_c, rtol=2e-4, atol=2e-5)

    # head-divisibility guard
    import pytest
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q[:, :3], k[:, :3], v[:, :3], mesh)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_flash_matches_dense(causal):
    """use_flash routes each ring step through the Pallas kernel
    (interpret mode on CPU) with exact (out, lse) merging."""
    onp.random.seed(3)
    b, h, t, d = 1, 2, 32, 8  # 4 per device over the 8-way ring
    q = onp.random.randn(b, h, t, d).astype(onp.float32)
    k = onp.random.randn(b, h, t, d).astype(onp.float32)
    v = onp.random.randn(b, h, t, d).astype(onp.float32)
    mesh = make_mesh({"sp": 8})
    out = ring_attention(mx.np.array(q), mx.np.array(k), mx.np.array(v),
                         mesh, axis_name="sp", causal=causal,
                         use_flash=True)
    expect = _dense_attention(q, k, v, causal=causal)
    assert onp.allclose(out.asnumpy(), expect, atol=2e-4), \
        onp.abs(out.asnumpy() - expect).max()


def test_ring_attention_flash_gradients_match_einsum_path():
    """The flash ring path must be differentiable (custom-vjp kernels
    under scan/cond/ppermute) and agree with the einsum ring path."""
    from mxnet_tpu import autograd

    onp.random.seed(4)
    b, h, t, d = 1, 2, 16, 8
    qn = onp.random.randn(b, h, t, d).astype(onp.float32)
    kn = onp.random.randn(b, h, t, d).astype(onp.float32)
    vn = onp.random.randn(b, h, t, d).astype(onp.float32)
    mesh = make_mesh({"sp": 4})
    grads = {}
    for flash in (False, True):
        q = mx.np.array(qn); k = mx.np.array(kn); v = mx.np.array(vn)
        for a in (q, k, v):
            a.attach_grad()
        with autograd.record():
            out = ring_attention(q, k, v, mesh, axis_name="sp",
                                 causal=True, use_flash=flash)
            loss = (out * out).sum()
        loss.backward()
        grads[flash] = [a.grad.asnumpy().copy() for a in (q, k, v)]
    for ge, gf in zip(grads[False], grads[True]):
        assert onp.allclose(ge, gf, atol=5e-4), onp.abs(ge - gf).max()


def _dense_masked(q, k, v, mask, causal=False):
    d = q.shape[-1]
    s = onp.einsum("bhqd,bhkd->bhqk", q, k) / onp.sqrt(d)
    if causal:
        t = s.shape[-1]
        s = onp.where(onp.tril(onp.ones((t, t), bool)), s, -1e30)
    s = onp.where(mask[:, None, None, :] != 0, s, -1e30)
    s = s - s.max(-1, keepdims=True)
    p = onp.exp(s)
    p /= p.sum(-1, keepdims=True)
    return onp.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("use_flash", [False, True])
def test_ring_attention_padding_mask_matches_dense(use_flash):
    """Round 6: a (B, T) key-padding mask shards over sp and rotates
    with K/V; both ring bodies must reproduce the dense masked softmax
    (ragged lengths spanning shard boundaries)."""
    onp.random.seed(5)
    b, h, t, d = 2, 2, 32, 8  # 4 keys per device over the 8-way ring
    q = onp.random.randn(b, h, t, d).astype(onp.float32)
    k = onp.random.randn(b, h, t, d).astype(onp.float32)
    v = onp.random.randn(b, h, t, d).astype(onp.float32)
    lens = onp.array([13, 32])  # one mid-shard cut, one full row
    mask = (onp.arange(t)[None, :] < lens[:, None]).astype(onp.int32)
    mesh = make_mesh({"sp": 8})
    out = ring_attention(mx.np.array(q), mx.np.array(k), mx.np.array(v),
                         mesh, axis_name="sp", use_flash=use_flash,
                         mask=mx.np.array(mask))
    expect = _dense_masked(q, k, v, mask)
    assert onp.allclose(out.asnumpy(), expect, atol=2e-4), \
        onp.abs(out.asnumpy() - expect).max()


def test_ring_attention_masked_flash_gradients_match_einsum_path():
    """Masked flash ring path: differentiable, agrees with the masked
    einsum ring body (fwd + dq/dk/dv), including a shard whose K block
    is ENTIRELY padded (lse sentinel weighs it out of the merge)."""
    from mxnet_tpu import autograd

    onp.random.seed(6)
    b, h, t, d = 1, 2, 16, 8
    qn = onp.random.randn(b, h, t, d).astype(onp.float32)
    kn = onp.random.randn(b, h, t, d).astype(onp.float32)
    vn = onp.random.randn(b, h, t, d).astype(onp.float32)
    # 4 keys per device; len 7 pads shard 1 partially and shards 2-3 fully
    mask = (onp.arange(t)[None, :] < 7).astype(onp.int32)
    mesh = make_mesh({"sp": 4})
    grads = {}
    for flash in (False, True):
        q = mx.np.array(qn); k = mx.np.array(kn); v = mx.np.array(vn)
        for a in (q, k, v):
            a.attach_grad()
        with autograd.record():
            out = ring_attention(q, k, v, mesh, axis_name="sp",
                                 use_flash=flash,
                                 mask=mx.np.array(mask))
            loss = (out * out).sum()
        loss.backward()
        grads[flash] = [a.grad.asnumpy().copy() for a in (q, k, v)]
        assert all(onp.isfinite(g).all() for g in grads[flash])
    for ge, gf in zip(grads[False], grads[True]):
        assert onp.allclose(ge, gf, atol=5e-4), onp.abs(ge - gf).max()


def test_mha_sp_path_threads_padding_mask(monkeypatch):
    """MultiHeadAttention.bind_sp_mesh no longer rejects (B, T) masks:
    the padding mask is handed to ring_attention (where the tests above
    prove the ring math), and full attention masks still raise.  Spied
    rather than run end-to-end: the eager sp path needs mesh-placed
    inputs (the product recipe drives it via FusedTrainStep(mesh=...),
    covered by test_sp_model_parity)."""
    import pytest as _pt

    import importlib

    from mxnet_tpu.models import transformer as tr
    # the package re-exports the FUNCTION under the module's name; fetch
    # the module itself to patch its namespace
    ra_mod = importlib.import_module("mxnet_tpu.parallel.ring_attention")

    onp.random.seed(7)
    x = mx.np.array(onp.random.randn(2, 16, 16).astype(onp.float32))
    mask = mx.np.array(
        (onp.arange(16)[None, :] < onp.array([[5], [16]])).astype(
            onp.int32))
    mesh = make_mesh({"sp": 4})
    seen = {}

    def spy(q, k, v, mesh, **kw):
        seen.update(kw)
        return q  # same (B, H, T, D) shape; math proven above

    monkeypatch.setattr(ra_mod, "ring_attention", spy)
    b = tr.MultiHeadAttention(16, 4, dropout=0.0).bind_sp_mesh(mesh)
    b.initialize()
    out = b(x, mask)
    assert out.shape == (2, 16, 16)
    assert seen.get("mask") is mask
    with _pt.raises(ValueError, match="key-padding"):
        b(x, mx.np.ones((2, 16, 16)))
