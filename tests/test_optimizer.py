"""Optimizer zoo tests.

Reference strategy: `tests/python/unittest/test_optimizer.py` compares each
fused update kernel against a python reference implementation.  Here every
registered optimizer minimizes the same convex quadratic — a convergence
oracle that exercises state creation, the update rule, lr/wd plumbing, and
in-place rebinding in one sweep.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt_mod
from mxnet_tpu.optimizer import Optimizer, create, get_updater

# name -> (kwargs, steps, tol) tuned so each rule reaches the optimum of w^2
_CONFIGS = {
    "sgd": (dict(learning_rate=0.1, momentum=0.9), 200, 0.1),
    "nag": (dict(learning_rate=0.1, momentum=0.9), 200, 0.1),
    # sign updates oscillate at lr scale around the optimum
    "signum": (dict(learning_rate=0.05, momentum=0.0), 600, 0.2),
    # SGLD samples the posterior exp(-w^2) (std ~0.7/coord), it does not
    # converge pointwise; require a strong contraction from |w0|=5 only
    "sgld": (dict(learning_rate=0.01), 800, 3.0),
    # LARS trust ratio ~ eta*||w||/||g|| shrinks the effective step; a toy
    # quadratic needs a large base lr
    "lars": (dict(learning_rate=5.0, eta=0.1, momentum=0.0), 200, 0.1),
    "dcasgd": (dict(learning_rate=0.1), 400, 0.1),
    "adam": (dict(learning_rate=0.3), 200, 0.1),
    "adamw": (dict(learning_rate=0.3), 200, 0.1),
    "adamax": (dict(learning_rate=0.3), 200, 0.1),
    "nadam": (dict(learning_rate=0.3), 200, 0.1),
    "lamb": (dict(learning_rate=0.1), 400, 0.1),
    "lans": (dict(learning_rate=0.1), 400, 0.1),
    "rmsprop": (dict(learning_rate=0.1), 200, 0.1),
    "adagrad": (dict(learning_rate=1.0), 400, 0.1),
    "adadelta": (dict(learning_rate=1.0, rho=0.9), 800, 0.1),
    "ftrl": (dict(learning_rate=1.0), 400, 0.1),
    "ftml": (dict(learning_rate=0.5), 500, 0.1),
}


@pytest.mark.parametrize("name", sorted(_CONFIGS))
def test_optimizer_minimizes_quadratic(name):
    kwargs, steps, tol = _CONFIGS[name]
    opt = create(name, **kwargs)
    w = mx.np.array([5.0, -3.0])
    state = opt.create_state(0, w)
    for _ in range(steps):
        grad = 2 * w  # d/dw sum(w^2)
        opt.update([0], [w], [grad], [state])
    final = float(abs(w).asnumpy().max())
    assert final < tol, f"{name} ended at |w|={final}"


def test_registry_covers_reference_set():
    """The 17 reference optimizers (python/mxnet/optimizer/) all resolve."""
    for name in ["sgd", "nag", "adam", "adamw", "adamax", "nadam", "lamb",
                 "lans", "lars", "ftrl", "ftml", "signum", "dcasgd",
                 "adagrad", "adadelta", "rmsprop", "sgld", "test"]:
        assert isinstance(create(name), Optimizer), name


def test_updater_state_roundtrip():
    opt = create("adam", learning_rate=0.1)
    upd = get_updater(opt)
    w = mx.np.array([1.0, 2.0])
    upd(0, 2 * w, w)
    blob = upd.get_states(dump_optimizer=True)

    upd2 = get_updater(create("adam"))
    upd2.set_states(blob)
    assert 0 in upd2.states
    assert upd2.optimizer.lr == 0.1
    # resumed updater keeps optimizing without re-creating state
    upd2(0, 2 * w, w)


def test_lr_wd_mult():
    opt = create("sgd", learning_rate=1.0, wd=0.1)
    opt.set_lr_mult({0: 0.5})
    opt.set_wd_mult({0: 0.0})
    assert opt._get_lr(0) == 0.5
    assert opt._get_wd(0) == 0.0
