"""Legacy top-level module parity: operator (CustomOp), dlpack, engine,
name/attribute scopes, error classes, libinfo.

Reference strategy: `tests/python/unittest/test_operator.py::test_custom_op`,
`test_dlpack`.
"""
import numpy as onp
import pytest
import torch

import mxnet_tpu as mx
from mxnet_tpu import autograd


def test_custom_op_forward_backward():
    @mx.operator.register("scale2")
    class Scale2Prop(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)

        def create_operator(self, ctx, in_shapes, in_dtypes):
            class Scale2(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0], in_data[0] * 2.0)

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    self.assign(in_grad[0], req[0], out_grad[0] * 2.0)
            return Scale2()

    x = mx.np.array(onp.array([1.0, 2.0, 3.0], onp.float32))
    out = mx.nd.Custom(x, op_type="scale2")
    assert onp.allclose(out.asnumpy(), [2.0, 4.0, 6.0])

    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type="scale2")
        loss = (y * mx.np.array(onp.array([1.0, 10.0, 100.0], onp.float32))).sum()
    loss.backward()
    assert onp.allclose(x.grad.asnumpy(), [2.0, 20.0, 200.0])


def test_custom_op_unregistered_raises():
    with pytest.raises(ValueError, match="not registered"):
        mx.nd.Custom(mx.np.array(onp.zeros(2, onp.float32)),
                     op_type="nope_xyz")


def test_dlpack_roundtrip_with_torch():
    x = mx.np.array(onp.arange(6, dtype=onp.float32).reshape(2, 3))
    t = torch.utils.dlpack.from_dlpack(mx.dlpack.to_dlpack_for_read(x))
    assert torch.allclose(t, torch.arange(6, dtype=torch.float32).view(2, 3))

    src = torch.full((3,), 7.0)
    back = mx.dlpack.from_dlpack(src)
    assert onp.allclose(back.asnumpy(), onp.full(3, 7.0))


def test_engine_bulk_scope():
    prev = mx.engine.set_bulk_size(16)
    assert mx.engine.set_bulk_size(prev) == 16
    with mx.engine.bulk(8):
        pass  # advisory on TPU; must roundtrip without error


def test_name_manager_and_prefix():
    nm = mx.name.NameManager()
    with nm:
        assert nm.get(None, "conv") == "conv0"
        assert nm.get(None, "conv") == "conv1"
        assert nm.get("explicit", "conv") == "explicit"
    with mx.name.Prefix("net_"):
        assert mx.name.current().get(None, "fc") == "net_fc0"
        # the reference Prefix namespaces explicit names too
        assert mx.name.current().get("fc9", "fc") == "net_fc9"


def test_attr_scope_nesting():
    with mx.attribute.AttrScope(group="a"):
        assert mx.attribute.current().get()["group"] == "a"
        with mx.attribute.AttrScope(lr_mult="2"):
            got = mx.attribute.current().get()
            assert got["group"] == "a" and got["lr_mult"] == "2"
        assert "lr_mult" not in mx.attribute.current().get()


def test_error_classes_and_version():
    assert issubclass(mx.error.ValueError, mx.MXNetError)
    assert issubclass(mx.error.ValueError, ValueError)
    assert mx.__version__.startswith("2.")
    assert isinstance(mx.libinfo.find_lib_path(), list)
